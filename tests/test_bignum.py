"""Known-answer tests for tier-0 limb arithmetic vs python ints.

The reference has no test suite (SURVEY.md §4); these are the unit layer of
the test pyramid we add: every kernel is checked against `pow()` / int
arithmetic on randomized operands at several key sizes.
"""

import random

import numpy as np
import pytest

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx

rng = random.Random(0xDD5)


def rand_odd(bits):
    n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    return n


def rand_below(n, k):
    return [rng.randrange(n) for _ in range(k)]


def test_limb_roundtrip():
    for bits in (16, 64, 256, 2048):
        L = bn.n_limbs_for_bits(bits)
        xs = [rng.getrandbits(bits) for _ in range(5)] + [0, 1, (1 << bits) - 1]
        batch = bn.ints_to_batch(xs, L)
        assert bn.batch_to_ints(batch) == xs


def test_add_sub():
    L = 16
    n = 1 << (16 * L)
    a_int = [rng.randrange(n) for _ in range(8)]
    b_int = [rng.randrange(n) for _ in range(8)]
    a, b = bn.ints_to_batch(a_int, L), bn.ints_to_batch(b_int, L)
    s, carry = bn.add(a, b)
    for i in range(8):
        total = a_int[i] + b_int[i]
        assert bn.limbs_to_int(np.asarray(s)[i]) == total % n
        assert int(carry[i]) == total // n
    d, borrow = bn.sub(a, b)
    for i in range(8):
        diff = a_int[i] - b_int[i]
        assert int(borrow[i]) == (1 if diff < 0 else 0)
        assert bn.limbs_to_int(np.asarray(d)[i]) == diff % n


@pytest.mark.parametrize("bits", [64, 256, 1024, 2048])
def test_mont_mul(bits):
    n = rand_odd(bits)
    ctx = ModCtx.make(n)
    B = 4
    a_int, b_int = rand_below(n, B), rand_below(n, B)
    a = bn.ints_to_batch(a_int, ctx.L)
    b = bn.ints_to_batch(b_int, ctx.L)
    out = ctx.mul_mod(a, b)
    got = bn.batch_to_ints(out)
    want = [(x * y) % n for x, y in zip(a_int, b_int)]
    assert got == want


@pytest.mark.parametrize("bits", [64, 256, 1024])
def test_mont_domain_roundtrip(bits):
    n = rand_odd(bits)
    ctx = ModCtx.make(n)
    xs = rand_below(n, 3) + [0, 1, n - 1]
    x = bn.ints_to_batch(xs, ctx.L)
    back = ctx.from_mont(ctx.to_mont(x))
    assert bn.batch_to_ints(back) == xs


@pytest.mark.parametrize("bits,ebits", [(64, 64), (256, 256), (1024, 64)])
def test_mont_exp(bits, ebits):
    n = rand_odd(bits)
    ctx = ModCtx.make(n)
    exp = rng.getrandbits(ebits)
    xs = rand_below(n, 4)
    x = bn.ints_to_batch(xs, ctx.L)
    got = bn.batch_to_ints(ctx.pow_mod(x, exp))
    assert got == [pow(v, exp, n) for v in xs]


def test_mont_exp_edge_exponents():
    n = rand_odd(256)
    ctx = ModCtx.make(n)
    xs = rand_below(n, 3)
    x = bn.ints_to_batch(xs, ctx.L)
    assert bn.batch_to_ints(ctx.pow_mod(x, 0)) == [1, 1, 1]
    assert bn.batch_to_ints(ctx.pow_mod(x, 1)) == xs
    assert bn.batch_to_ints(ctx.pow_mod(x, 2)) == [v * v % n for v in xs]
    assert bn.batch_to_ints(ctx.pow_mod(x, 65537)) == [pow(v, 65537, n) for v in xs]


@pytest.mark.parametrize("K", [1, 2, 3, 7, 8, 16, 33])
def test_reduce_mul(K):
    n = rand_odd(512)
    ctx = ModCtx.make(n)
    cs_int = rand_below(n, K)
    cs = bn.ints_to_batch(cs_int, ctx.L)
    got = bn.limbs_to_int(np.asarray(ctx.reduce_mul(cs))[0])
    want = 1
    for c in cs_int:
        want = want * c % n
    assert got == want


def test_scalar_mul_small():
    L = 16
    n_max = 1 << (16 * L)
    xs = [rng.randrange(n_max) for _ in range(4)]
    ss = [rng.randrange(1 << 16) for _ in range(4)]
    import jax.numpy as jnp

    out = bn.scalar_mul_small(
        bn.ints_to_batch(xs, L), jnp.asarray(np.array(ss, np.uint32))
    )
    for i in range(4):
        assert bn.limbs_to_int(np.asarray(out)[i]) == xs[i] * ss[i]
