"""Panopticon fleet-observability plane tests.

Unit layer (in-process, tier-1): span-shipper spool/drop accounting and
batch MACs, collector MAC rejection, cross-host trace stitching replayed
into a Watchtower (forged stale tag over a simulated TCP hop -> exactly
the tag_monotonicity + quorum_intersection verdicts; the honest schedule
is verdict-free), Prometheus exposition federation/relabeling, fleet SLO
burn rollup (worst-of and sum-of), incident correlation by trace id, the
`dds_process_info` identity gauge, the hostile-`tc`-frame ingest clamp,
and the sentry `fleet obs` record contract. Flagship layer (slow): a
3-OS-process loopback Meridian fleet with one group's replicas armed as
stale-tag forgers — the proxy's collector-fed Watchtower must catch the
forgery across real sockets, and the identical clean fleet must not.
"""

import asyncio
import json
import os
import time

import pytest

from dds_tpu.core import messages as M
from dds_tpu.obs import context as obs_context
from dds_tpu.obs.metrics import Registry, metrics
from dds_tpu.obs.panopticon import (FleetCollector, NullWatchtower,
                                    SpanShipper, batch_mac, merge_expositions,
                                    parse_samples, process_info,
                                    record_from_dict)
from dds_tpu.obs.watchtower import Watchtower
from dds_tpu.utils import sigs
from dds_tpu.utils.tasks import supervised_task
from dds_tpu.utils.trace import SpanRecord, Tracer

pytestmark = pytest.mark.obs

SECRET = b"panopticon-test-secret"


def run(coro):
    return asyncio.run(coro)


class LoopNet:
    """Transport stub with the TcpNet surface the plane uses: endpoint
    registry keyed by name, local_addr() composition, and fire-and-forget
    send that records every frame and dispatches registered handlers."""

    def __init__(self, advertised="127.0.0.1:1"):
        self.advertised = advertised
        self.handlers = {}
        self.sent = []

    def local_addr(self, name: str) -> str:
        return f"{self.advertised}/{name}"

    def register(self, addr: str, handler) -> None:
        self.handlers[addr.rsplit("/", 1)[-1]] = handler

    def unregister(self, addr: str) -> None:
        self.handlers.pop(addr.rsplit("/", 1)[-1], None)

    def send(self, src: str, dest: str, msg) -> None:
        self.sent.append((src, dest, msg))
        h = self.handlers.get(dest.rsplit("/", 1)[-1])
        if h is not None:
            supervised_task(h(src, msg), name="loopnet.deliver")


def make_shipper(net=None, tracer=None, registry=None, **kw):
    net = net if net is not None else LoopNet("127.0.0.1:71")
    tracer = tracer if tracer is not None else Tracer()
    registry = registry if registry is not None else Registry()
    kw.setdefault("collector", "127.0.0.1:70")
    kw.setdefault("secret", SECRET)
    kw.setdefault("host", "127.0.0.1:71")
    kw.setdefault("role", "group:s0")
    kw.setdefault("shard", "s0")
    kw.setdefault("flush_interval", 0.01)
    sh = SpanShipper(net, tracer=tracer, registry=registry, **kw)
    return sh, net, tracer, registry


def make_batch(trees, *, host="ghost", role="group:s0", shard="s0", seq=1,
               incidents=(), metrics_text="", slo=None, dropped=0,
               secret=SECRET):
    slo = slo if slo is not None else {}
    incidents = list(incidents)
    mac = batch_mac(secret, host, role, shard, seq, 123.0, trees, incidents,
                    metrics_text, slo, dropped)
    return M.TelemetryBatch(host=host, role=role, shard=shard, seq=seq,
                            ts=123.0, spans=trees, incidents=incidents,
                            metrics_text=metrics_text, slo=slo,
                            dropped=dropped, mac=mac)


# ----------------------------------------------------------- identity gauge


def test_process_info_gauge_carries_identity_labels():
    reg = Registry()
    process_info(reg, role="group:s0", shard="s0")
    samples = parse_samples(reg.render(), "dds_process_info")
    assert len(samples) == 1
    labels, value = samples[0]
    assert value == 1.0
    assert labels["role"] == "group:s0" and labels["shard"] == "s0"
    assert labels["pid"] == str(os.getpid())
    assert float(labels["start_ts"]) > 0
    assert labels["version"]

    # no shard -> placeholder label, never an empty value
    reg2 = Registry()
    process_info(reg2, role="proxy")
    (labels2, _), = parse_samples(reg2.render(), "dds_process_info")
    assert labels2["shard"] == "-"


# ------------------------------------------------------------ wire helpers


def test_record_from_dict_roundtrips_and_survives_garbage():
    t = Tracer()
    with t.span("abd.fetch", key="K"):
        pass
    d = Tracer.event_dict(t.events()[0])
    rec = record_from_dict(d)
    assert isinstance(rec, SpanRecord)
    assert rec.name == "abd.fetch" and rec.meta == {"key": "K"}
    assert rec.trace_id == d["trace_id"] and rec.parent_id is None

    assert record_from_dict({}) is None
    assert record_from_dict({"name": "x"}) is None          # no ts
    assert record_from_dict({"ts": None, "name": "x"}) is None
    assert record_from_dict({"ts": "junk", "name": "x"}) is None
    # non-dict meta degrades to {} instead of poisoning the audit
    ok = record_from_dict({"ts": 1.0, "name": "x", "meta": ["not-a-dict"]})
    assert ok is not None and ok.meta == {}


def test_batch_mac_is_payload_sensitive():
    args = ("h", "group:s0", "s0", 1, 2.0, [["x"]], [], "m", {}, 0)
    base = batch_mac(SECRET, *args)
    assert base == batch_mac(SECRET, *args)
    assert base != batch_mac(b"other-key", *args)
    tampered = ("h", "group:s0", "s0", 1, 2.0, [["y"]], [], "m", {}, 0)
    assert base != batch_mac(SECRET, *tampered)


# ------------------------------------------------------------------ shipper


def test_shipper_ships_quiesced_trees_as_signed_batches():
    async def go():
        sh, net, t, reg = make_shipper()
        t.subscribe(sh.on_record)
        with t.span("replica.handle", replica="s0-replica-1", msg="Read",
                    key="K"):
            pass
        t.unsubscribe(sh.on_record)
        await asyncio.sleep(0.03)  # quiesce past the flush interval
        await sh._flush_once()
        assert len(net.sent) == 1
        src, dest, batch = net.sent[0]
        assert dest == "127.0.0.1:70/panopticon"
        assert isinstance(batch, M.TelemetryBatch)
        assert (batch.host, batch.role, batch.shard) == \
            ("127.0.0.1:71", "group:s0", "s0")
        assert batch.seq == 1 and batch.dropped == 0
        names = [d["name"] for tree in batch.spans for d in tree]
        assert names == ["replica.handle"]
        # the MAC covers exactly the shipped payload
        assert batch.mac == batch_mac(
            SECRET, batch.host, batch.role, batch.shard, batch.seq, batch.ts,
            batch.spans, batch.incidents, batch.metrics_text, batch.slo,
            batch.dropped,
        )
        # nothing new + heartbeat not yet due -> no frame
        await sh._flush_once()
        assert len(net.sent) == 1
        # heartbeat due -> empty-span liveness batch carrying the process
        # metrics snapshot even with no local SloEngine
        sh._last_ship = 0.0
        await sh._flush_once()
        assert len(net.sent) == 2 and net.sent[1][2].spans == []
        assert "dds_fleet_ship_batches_total" in net.sent[1][2].metrics_text

    run(go())


def test_shipper_spool_overflow_drops_oldest_and_accounts():
    async def go():
        sh, net, t, reg = make_shipper(spool_max=2, batch_max=1)
        t.subscribe(sh.on_record)
        for i in range(4):
            with t.span(f"op{i}"):  # four distinct single-span traces
                pass
        t.unsubscribe(sh.on_record)
        assert sh.stats()["active_traces"] == 4
        await asyncio.sleep(0.03)
        trees = sh._collect_quiesced()
        # batch_max caps the flight; spool_max bounds the backlog: of the
        # four quiesced trees one ships, one stays spooled, two dropped
        assert len(trees) == 1 and sh.stats()["spooled_trees"] == 1
        assert sh.stats()["dropped"] == 2
        assert reg.value("dds_fleet_ship_dropped_total",
                         reason="spool_overflow") == 2

        # a rejecting ack is a drop too — accounted, never retried
        await sh.handle("c", M.TelemetryAck(seq=9, ok=False, error="bad mac"))
        assert sh.stats()["dropped"] == 3
        assert reg.value("dds_fleet_ship_dropped_total", reason="rejected") == 1

    run(go())


def test_shipper_never_ships_breaker_noise_without_trace_but_keeps_events():
    async def go():
        sh, net, t, reg = make_shipper()
        t.subscribe(sh.on_record)
        t.event("breaker.open", target="s0-replica-2")     # loose: shipped
        t.record("cache.miss", 0.0, _kind="event")         # loose: ignored
        t.unsubscribe(sh.on_record)
        await asyncio.sleep(0.03)
        await sh._flush_once()
        (_, _, batch), = net.sent
        names = [d["name"] for tree in batch.spans for d in tree]
        assert names == ["breaker.open"]

    run(go())


# ---------------------------------------------------------------- collector


def make_collector(net=None, wt=None, tracer=None, registry=None, **kw):
    net = net if net is not None else LoopNet("127.0.0.1:70")
    wt = wt if wt is not None else Watchtower(quorum_size=3, n_replicas=4)
    tracer = tracer if tracer is not None else Tracer()
    registry = registry if registry is not None else Registry()
    kw.setdefault("secret", SECRET)
    kw.setdefault("host", "127.0.0.1:70")
    kw.setdefault("stitch_window", 0.05)
    col = FleetCollector(net, watchtower=wt, tracer=tracer, registry=registry,
                         **kw)
    return col, net, wt, tracer, registry


def test_collector_rejects_bad_mac_with_ack_and_counter():
    async def go():
        col, net, wt, t, reg = make_collector()
        batch = make_batch([], secret=b"wrong-secret")
        await col.handle("g:1/panopticon-ship", batch)
        assert col.stats()["sources"] == []
        assert reg.value("dds_fleet_collect_rejected_total", reason="mac") == 1
        (_, dest, ack), = net.sent
        assert dest == "g:1/panopticon-ship"
        assert isinstance(ack, M.TelemetryAck)
        assert not ack.ok and ack.error == "bad mac" and ack.seq == 1

        # properly-signed batch from the same peer lands and acks ok
        await col.handle("g:1/panopticon-ship", make_batch([], seq=2))
        assert col.stats()["sources"] == ["ghost"]
        assert net.sent[-1][2].ok

    run(go())


def _commit(t, name, key, seq, tag_id, coordinator="s0-replica-0"):
    """Proxy-local half of a cross-host op: root http span + the quorum
    client's committed abd span. Returns the abd span's context so remote
    handler spans can be forged as its children."""
    ctx = {}
    with t.span(f"http.{name}"):
        with t.span(
            "abd.write" if name == "write" else "abd.fetch",
            coordinator=coordinator, ok=True,
            op="write" if name == "write" else "read",
            key=key, seq=seq, tag_id=tag_id,
        ):
            ctx["abd"] = obs_context.current()
    return ctx["abd"]


def _remote_handlers(ctx, phases):
    """Shipped replica.handle spans (a remote group process's vantage),
    children of the proxy's abd span via the propagated tc context."""
    return [
        {
            "ts": time.time(), "name": "replica.handle", "dur_ms": 0.3,
            "kind": "span", "trace_id": ctx.trace_id,
            "span_id": os.urandom(8).hex(), "parent_id": ctx.span_id,
            "meta": {"replica": replica, "msg": msg, "key": "K"},
        }
        for msg, replica in phases
    ]


R4 = [f"s0-replica-{i}" for i in range(4)]


def test_collector_stitches_cross_host_trace_and_audits_forgery():
    """Satellite-c in-process smoke: two honest cross-host write commits
    (handler spans arrive by TelemetryBatch, not the local tracer), then a
    read committing a forged stale tag with NO remote quorum behind it.
    The collector-fed Watchtower must emit exactly tag_monotonicity +
    quorum_intersection, both blaming the forged read's trace."""

    async def go():
        col, net, wt, t, reg = make_collector()
        t.subscribe(col.on_record)
        seq = 0
        for wseq in (1, 2):
            ctx = _commit(t, "write", "K", wseq, "s0-replica-0")
            seq += 1
            tree = _remote_handlers(
                ctx,
                [("ReadTag", r) for r in R4[:3]]
                + [("Write", r) for r in R4[:3]],
            )
            await col.handle("g/panopticon-ship", make_batch([tree], seq=seq))
            await asyncio.sleep(0.06)  # past the stitch window
            col._replay_due()
            await asyncio.sleep(0.005)  # strict real-time commit order
        assert wt.verdicts() == []
        assert col.stats()["traces_stitched"] == 2

        # the forgery: a committed stale read no remote process vouches for
        _commit(t, "read", "K", 1, "forged", coordinator="s0-replica-3")
        await asyncio.sleep(0.06)
        col._replay_due()
        vs = wt.verdicts()
        by_inv = {v.invariant: v for v in vs}
        assert set(by_inv) == {"tag_monotonicity", "quorum_intersection"}
        assert by_inv["tag_monotonicity"].detail["tag"] == [1, "forged"]
        tid = by_inv["tag_monotonicity"].trace_id
        assert tid is not None
        assert by_inv["quorum_intersection"].trace_id == tid
        t.unsubscribe(col.on_record)

    run(go())


def test_collector_audits_each_trace_once_despite_stragglers():
    async def go():
        col, net, wt, t, reg = make_collector()
        t.subscribe(col.on_record)
        ctx = _commit(t, "write", "K", 1, "s0-replica-0")
        tree = _remote_handlers(
            ctx, [("ReadTag", r) for r in R4[:3]] + [("Write", r) for r in R4[:3]]
        )
        await col.handle("g/panopticon-ship", make_batch([tree], seq=1))
        await asyncio.sleep(0.06)
        col._replay_due()
        assert col.stats()["traces_stitched"] == 1
        # a straggler span for the audited trace must not re-open it
        await col.handle("g/panopticon-ship",
                         make_batch([tree[:1]], seq=2))
        await asyncio.sleep(0.06)
        col._replay_due()
        assert col.stats()["traces_stitched"] == 1
        assert col.stats()["pending_traces"] == 0
        assert wt.verdicts() == []
        t.unsubscribe(col.on_record)

    run(go())


def test_null_watchtower_sinks_replays():
    async def go():
        sink = NullWatchtower()
        col, net, _, t, reg = make_collector(wt=sink)
        t.subscribe(col.on_record)
        _commit(t, "read", "K", 1, "forged")
        await asyncio.sleep(0.06)
        col._replay_due()
        assert col.stats()["traces_stitched"] == 1
        assert sink.verdicts() == []
        t.unsubscribe(col.on_record)

    run(go())


# --------------------------------------------------------------- federation


def test_merge_expositions_relabels_and_emits_headers_once():
    src_a = (
        "# HELP dds_requests_total requests\n"
        "# TYPE dds_requests_total counter\n"
        'dds_requests_total{route="GetSet"} 3\n'
    )
    src_b = (
        "# HELP dds_requests_total requests\n"
        "# TYPE dds_requests_total counter\n"
        "dds_requests_total 5\n"
        "# TYPE dds_lat histogram\n"
        'dds_lat_bucket{le="+Inf"} 2\n'
        "dds_lat_sum 0.25\n"
        "dds_lat_count 2\n"
    )
    doc = merge_expositions([
        {"labels": {"host": "h1", "role": "proxy"}, "text": src_a},
        {"labels": {"host": "h2", "role": "group:s0", "shard": "s0"},
         "text": src_b},
    ])
    assert doc.count("# HELP dds_requests_total") == 1
    assert doc.count("# TYPE dds_requests_total counter") == 1
    assert 'dds_requests_total{host="h1",role="proxy",route="GetSet"} 3' in doc
    assert ('dds_requests_total{host="h2",role="group:s0",shard="s0"} 5'
            in doc)
    # histogram suffix lines stay grouped under their family, relabeled
    lines = doc.splitlines()
    fam_at = lines.index("# TYPE dds_lat histogram")
    assert lines[fam_at + 1].startswith('dds_lat_bucket{host="h2"')
    assert 'dds_lat_sum{host="h2",role="group:s0",shard="s0"} 0.25' in doc
    assert 'dds_lat_count{host="h2",role="group:s0",shard="s0"} 2' in doc


def test_parse_samples_reads_labeled_and_bare_series():
    reg = Registry()
    reg.set("dds_resident_rows", 42, shard="s0")
    reg.set("dds_resident_rows", 7, shard="s1")
    reg.set("dds_admission_shed_level", 2)
    text = reg.render()
    rows = dict((lab["shard"], v)
                for lab, v in parse_samples(text, "dds_resident_rows"))
    assert rows == {"s0": 42.0, "s1": 7.0}
    assert parse_samples(text, "dds_admission_shed_level") == [({}, 2.0)]
    assert parse_samples(text, "dds_absent_series") == []


def test_fleet_metrics_labels_every_source_and_marks_staleness():
    async def go():
        col, net, wt, t, reg = make_collector(staleness=5.0)
        reg.set("dds_up", 1)
        await col.handle("g/panopticon-ship", make_batch(
            [], host="10.0.0.7:7100", role="group:s0", shard="s0",
            metrics_text="# TYPE dds_up gauge\ndds_up 1\n", dropped=3,
        ))
        col.sample_gauges()
        doc = col.fleet_metrics()
        assert 'dds_up{host="127.0.0.1:70",role="proxy"} 1' in doc
        assert ('dds_up{host="10.0.0.7:7100",role="group:s0",shard="s0"} 1'
                in doc)
        assert 'dds_fleet_source_stale{host="10.0.0.7:7100",' \
            'role="group:s0"} 0' in doc
        assert 'dds_fleet_ship_dropped_by_source{host="10.0.0.7:7100"} 3' \
            in doc
        # age the source past the staleness horizon
        col._sources["10.0.0.7:7100"]["mono"] -= 60.0
        doc = col.fleet_metrics()
        assert 'dds_fleet_source_stale{host="10.0.0.7:7100",' \
            'role="group:s0"} 1' in doc
        ages = parse_samples(doc, "dds_fleet_source_age_seconds")
        assert {a["host"] for a, _ in ages} == {"127.0.0.1:70",
                                                "10.0.0.7:7100"}

    run(go())


def test_fleet_slo_rolls_up_worst_of_and_sum_of_burn():
    async def go():
        col, net, wt, t, reg = make_collector()

        def slo_for(total, bad, burn):
            return {"routes": {"GetSet": {
                "objective": 0.99, "class": "interactive",
                "windows": {"5m": {"total": total, "bad": bad,
                                   "burn_rate": burn}},
            }}}

        await col.handle("a/s", make_batch(
            [], host="hA", role="group:s0", shard="s0", seq=1,
            slo=slo_for(100, 2, 2.0),
            metrics_text=('dds_resident_rows{shard="s0"} 10\n'
                          'dds_resident_bytes{shard="s0"} 4096\n'
                          "dds_admission_shed_level 1\n"),
        ))
        await col.handle("b/s", make_batch(
            [], host="hB", role="group:s1", shard="s1", seq=1,
            slo=slo_for(300, 0, 0.5),
            metrics_text=('dds_resident_rows{shard="s1"} 7\n'
                          "dds_admission_shed_level 3\n"),
        ))
        rep = col.fleet_slo()
        assert set(rep["hosts"]) == {"127.0.0.1:70", "hA", "hB"}
        assert rep["hosts"]["hA"]["role"] == "group:s0"
        w = rep["fleet"]["routes"]["GetSet"]["windows"]["5m"]
        assert w["total"] == 400 and w["bad"] == 2
        assert w["burn_rate_worst"] == 2.0
        # pooled: (2/400) / (1 - 0.99) = 0.5
        assert w["burn_rate_sum_of"] == 0.5
        assert rep["fleet"]["resident"]["s0"] == {
            "rows": 10.0, "host": "hA", "bytes": 4096.0,
        }
        assert rep["fleet"]["resident"]["s1"]["rows"] == 7.0
        assert rep["fleet"]["shed_level"] == {"hA": 1.0, "hB": 3.0}
        assert rep["fleet"]["shed_level_max"] == 3.0

    run(go())


def test_fleet_incidents_correlate_by_trace_id():
    async def go():
        col, net, wt, t, reg = make_collector()
        await col.handle("a/s", make_batch(
            [], host="hA", role="group:s0", shard="s0",
            incidents=[{"trace_id": "aa11", "reason": "audit"},
                       {"reason": "panic"}],
        ))
        await col.handle("b/s", make_batch(
            [], host="hB", role="group:s1", shard="s1",
            incidents=[{"trace_id": "aa11", "reason": "audit"}],
        ))
        rep = col.fleet_incidents()
        assert rep["count"] == 3
        # shipped entries are attributed to their source process
        assert {(e["host"], e["role"]) for e in rep["incidents"]} == {
            ("hA", "group:s0"), ("hB", "group:s1"),
        }
        # the fleet-wide why: both hosts' incidents share the trace
        assert [e["host"] for e in rep["by_trace"]["aa11"]] == ["hA", "hB"]
        only = col.fleet_incidents("aa11")
        assert only["count"] == 2 and set(only["by_trace"]) == {"aa11"}
        assert rep["verdicts"] == []

    run(go())


# ------------------------------------------- satellite-a: hostile tc ingest


def test_hostile_tc_frame_field_is_clamped_counted_and_non_fatal():
    """An unauthenticated peer spraying malformed `tc` fields must not
    drop messages or kill the shared connection: every frame dispatches,
    the garbage degrades to an unlinked span context, and the malformed
    counter accounts each refusal."""
    from dds_tpu.core.transport import TcpNet

    async def go():
        net = TcpNet("127.0.0.1", 0)
        await net.start()
        got = []

        async def handler(src, msg):
            got.append((msg.seq, obs_context.current()))

        net.register(net.local_addr("victim"), handler)
        before = metrics.value("dds_trace_context_malformed_total") or 0
        try:
            _, writer = await asyncio.open_connection("127.0.0.1", net.port)
            hostile = [
                "garbage-not-a-dict",
                {"t": "gg" * 8, "s": "ab12" * 4},   # non-hex chars
                {"t": "a" * 40, "s": "ab12" * 4},   # oversized id
                {"t": "ab12" * 4, "s": 12345},      # non-string id
            ]
            frames = [(i, tc) for i, tc in enumerate(hostile)]
            frames.append((4, {"t": "ab12" * 4, "s": "cd34" * 4}))  # valid
            frames.append((5, None))                                # absent
            for seq, tc in frames:
                obj = {
                    "src": "10.6.6.6:666/evil",
                    "dest": f"{net.advertised}/victim",
                    "msg": M.to_dict(M.TelemetryAck(seq=seq, ok=True)),
                }
                if tc is not None:
                    obj["tc"] = tc
                frame = json.dumps(obj).encode()
                writer.write(len(frame).to_bytes(4, "big") + frame)
            await writer.drain()
            deadline = time.monotonic() + 5.0
            while len(got) < 6 and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            writer.close()
        finally:
            await net.stop()

        # the connection survived: every message (incl. the ones behind
        # the garbage) dispatched, in order
        assert [seq for seq, _ in got] == [0, 1, 2, 3, 4, 5]
        by_seq = dict(got)
        # hostile contexts refused wholesale; valid one restored; absent
        # one simply unlinked
        for seq in (0, 1, 2, 3, 5):
            assert by_seq[seq] is None
        assert by_seq[4] is not None
        assert by_seq[4].trace_id == "ab12" * 4
        assert (metrics.value("dds_trace_context_malformed_total") or 0) \
            == before + 4

    run(go())


# -------------------------------------------- sentry `fleet obs` contract


def test_sentry_validates_fleet_obs_records(tmp_path):
    from benchmarks.sentry import _check_fleet_obs_records

    good = {
        "metric": "fleet obs", "value": 53.3, "unit": "req/s",
        "vs_baseline": 1.0,
        "detail": {
            "rate": 80.0, "duration": 2.0, "processes": 3,
            "open_loop": True, "on_good": 107, "off_good": 110,
            "overhead_pct": 2.73, "sources": 2, "stitched": 40,
            "dropped": 0,
        },
    }
    bench = tmp_path / "benchmarks"
    bench.mkdir()
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_fleet_obs_records(str(tmp_path)) == {"rows": 1}
    for mutate in (
        {"value": 0},                                       # no goodput
        {"detail": dict(good["detail"], processes=1)},      # not a fleet
        {"detail": dict(good["detail"], open_loop=False)},
        {"detail": dict(good["detail"], on_good=0)},        # nothing served
        {"detail": dict(good["detail"], overhead_pct="2%")},
        {"detail": dict(good["detail"], sources=0)},        # plane not live
        {"detail": dict(good["detail"], stitched=-1)},
        {"detail": dict(good["detail"], dropped=None)},     # unaccounted
    ):
        (bench / "results.json").write_text(json.dumps([dict(good, **mutate)]))
        with pytest.raises(ValueError):
            _check_fleet_obs_records(str(tmp_path))
    # absent files / other families never fail the smoke
    (bench / "results.json").write_text(json.dumps([{"metric": "sweep"}]))
    assert _check_fleet_obs_records(str(tmp_path)) == {"rows": 0}


# --------------------------------- flagship: real OS processes, real attack


def _fleet_key_owned_by(gid: str) -> tuple[list, str]:
    """(contents, key) for a PutSet whose content-hash key lands in `gid`
    under the fleet's deterministic epoch-1 map (S=2, default vnodes)."""
    from dds_tpu.shard.shardmap import ShardMap
    from dds_tpu.utils.config import DDSConfig

    smap = ShardMap.build(["s0", "s1"], DDSConfig().shard.vnodes_per_group)
    for i in range(4096):
        contents = [f"panopticon-{i}"]
        key = sigs.key_from_set(contents)
        if smap.owner(key) == gid:
            return contents, key
    raise AssertionError("no key hashed into the target group")


def _panopticon_fleet(workdir, attack: bool):
    from benchmarks.multihost_load import Fleet

    fleet = Fleet(str(workdir), proxy_audit=True)
    ship_stanza = (
        "\n[obs.fleet]\nenabled = true\n"
        f'collector = "{fleet.proxy_transport}"\n'
        "flush-interval = 0.1\n"
    )
    forge_stanza = '\n[attacks]\nenabled = true\ntype = "stale_tag"\n'
    fleet.group_extra = {
        gid: ship_stanza + (forge_stanza if attack and gid == "s0" else "")
        for gid in fleet.gids
    }
    fleet.proxy_extra = "\n[obs.fleet]\nenabled = true\nstitch-window = 1.5\n"
    return fleet


async def _forged_fleet_schedule(workdir, attack: bool):
    """Two honest writes then one read of an s0-owned key against a REAL
    3-OS-process loopback fleet; with `attack`, every s0 replica forges
    properly-MAC'd stale read replies. Returns (read contents, the
    /fleet/incidents report, the /fleet/metrics text)."""
    from dds_tpu.http.miniserver import http_request

    contents, key = _fleet_key_owned_by("s0")
    workdir.mkdir(parents=True, exist_ok=True)
    fleet = _panopticon_fleet(workdir, attack)
    try:
        fleet.start()
        await fleet.wait_healthy(timeout=120.0)
        port = int(fleet.proxy_targets[0].rsplit(":", 1)[1])
        for _ in range(2):  # same contents -> same key: two commits on it
            status, body = await http_request(
                "127.0.0.1", port, "POST", "/PutSet",
                json.dumps({"contents": contents}).encode(), timeout=30.0)
            assert status == 200 and body.decode() == key
            await asyncio.sleep(0.05)  # strict real-time commit order
        status, body = await http_request(
            "127.0.0.1", port, "GET", f"/GetSet/{key}", timeout=30.0)
        assert status == 200
        value = json.loads(body)["contents"]
        # let the group processes quiesce + ship (flush 0.1) and the
        # collector replay the stitched trees (stitch window 1.5)
        await asyncio.sleep(4.0)
        status, body = await http_request(
            "127.0.0.1", port, "GET", "/fleet/incidents", timeout=10.0)
        assert status == 200
        report = json.loads(body)
        status, mbody = await http_request(
            "127.0.0.1", port, "GET", "/fleet/metrics", timeout=10.0)
        assert status == 200
        return value, report, mbody.decode()
    finally:
        fleet.stop()


@pytest.mark.slow
@pytest.mark.multihost
def test_flagship_cross_host_stale_tag_forgery_is_caught(tmp_path):
    """Satellite-c acceptance on real OS processes: the s0 group process
    forges a stale read across the socket; the proxy's collector-fed
    Watchtower emits exactly tag_monotonicity + quorum_intersection, both
    blaming the forged read's cross-host trace."""
    value, report, mtext = run(
        _forged_fleet_schedule(tmp_path / "attack", attack=True)
    )
    assert value == ["stale"]  # the forgery really landed at the client
    verdicts = report["verdicts"]
    by_inv = {v["invariant"]: v for v in verdicts}
    assert set(by_inv) == {"tag_monotonicity", "quorum_intersection"}, verdicts
    assert by_inv["tag_monotonicity"]["detail"]["tag"] == [1, "forged"]
    tid = by_inv["tag_monotonicity"]["trace_id"]
    assert tid and by_inv["quorum_intersection"]["trace_id"] == tid
    # federation saw every host, labeled by role
    assert 'role="group:s0"' in mtext and 'role="group:s1"' in mtext
    assert 'role="proxy"' in mtext
    assert "dds_fleet_source_age_seconds" in mtext


@pytest.mark.slow
@pytest.mark.multihost
def test_flagship_clean_fleet_schedule_is_verdict_free(tmp_path):
    """The identical schedule minus the forgery: honest value served, the
    stitched cross-host traces audit clean (quorum checks ENABLED — the
    shipped replica handler spans are what makes them sound again)."""
    contents, _ = _fleet_key_owned_by("s0")
    value, report, mtext = run(
        _forged_fleet_schedule(tmp_path / "clean", attack=False)
    )
    assert value == contents
    assert report["verdicts"] == [], report["verdicts"]
    assert 'role="group:s0"' in mtext and 'role="proxy"' in mtext
