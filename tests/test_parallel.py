"""Mesh-sharded ciphertext ops on the virtual 8-device CPU mesh."""

import random

import numpy as np
import pytest

import jax

from dds_tpu.ops import bignum as bn
from dds_tpu.ops.montgomery import ModCtx, _exp_to_digits
from dds_tpu.parallel import make_mesh, sharded_pow_mod
from dds_tpu.parallel.mesh import sharded_reduce_mul_fixed

rng = random.Random(9)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("K", [8, 16, 37])
def test_sharded_reduce_mul_matches_int(K):
    n = rng.getrandbits(512) | (1 << 511) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    cs_int = [rng.randrange(n) for _ in range(K)]
    cs = bn.ints_to_batch(cs_int, ctx.L)
    out = sharded_reduce_mul_fixed(ctx, cs, mesh)
    want = 1
    for c in cs_int:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


@pytest.mark.parametrize("K", [8, 16, 37])
def test_ring_combine_matches_allgather(K):
    """The ppermute ring combine (ring-attention-style neighbor hops) must
    produce exactly the all_gather tree's result — same product, same
    Montgomery R accounting (D-1 multiplies either way)."""
    n = rng.getrandbits(512) | (1 << 511) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    cs_int = [rng.randrange(n) for _ in range(K)]
    cs = bn.ints_to_batch(cs_int, ctx.L)
    out = sharded_reduce_mul_fixed(ctx, cs, mesh, ring=True)
    want = 1
    for c in cs_int:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


def test_sharded_pow_mod_matches_int():
    n = rng.getrandbits(256) | (1 << 255) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    exp = rng.getrandbits(64)
    bases_int = [rng.randrange(n) for _ in range(16)]
    bases = bn.ints_to_batch(bases_int, ctx.L)
    out = sharded_pow_mod(ctx, bases, _exp_to_digits(exp), mesh)
    assert bn.batch_to_ints(np.asarray(out)) == [pow(b, exp, n) for b in bases_int]


def test_sharded_matches_single_device_path():
    n = rng.getrandbits(256) | (1 << 255) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    cs = bn.ints_to_batch([rng.randrange(n) for _ in range(24)], ctx.L)
    sharded = sharded_reduce_mul_fixed(ctx, cs, mesh)
    single = ctx.reduce_mul(cs)
    assert np.array_equal(np.asarray(sharded), np.asarray(single))


@pytest.mark.parametrize("D,K", [(3, 12), (5, 11), (7, 21)])
def test_sharded_reduce_non_power_of_two_mesh(D, K):
    """Regression: odd partial counts must pad with the Montgomery identity,
    not silently broadcast a short operand."""
    n = rng.getrandbits(256) | (1 << 255) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(D)
    cs_int = [rng.randrange(n) for _ in range(K)]
    out = sharded_reduce_mul_fixed(ctx, bn.ints_to_batch(cs_int, ctx.L), mesh)
    want = 1
    for c in cs_int:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


# ----------------------------------- scatter-gather tail combine edge cases


def test_combine_partials_empty_partition_raises():
    """An empty per-shard partition is a caller bug (the scatter path
    filters empty groups before dispatch): it must fail loudly, never
    invent a neutral result for an aggregate nobody computed."""
    from dds_tpu.parallel.mesh import combine_partials

    with pytest.raises(ValueError):
        combine_partials([], 97)


def test_combine_partials_single_shard_identity():
    """One shard owning every operand must combine to exactly its own
    partial (reduced mod n) — the S=1 degenerate case the router's
    single-group fast path relies on."""
    from dds_tpu.parallel.mesh import combine_partials

    n = rng.getrandbits(256) | (1 << 255) | 1
    p = rng.randrange(n)
    assert combine_partials([p], n) == p
    assert combine_partials([p + n], n) == p  # unreduced input normalizes


def test_combine_partials_neutral_elements():
    """Neutral-element handling for both aggregate families: a shard whose
    fold saw no effective operands contributes 1 (the modular-product
    identity) for SumAll (mod n^2 ciphertext adds) AND MultAll (mod n
    ciphertext products), and must never perturb the combined result."""
    from dds_tpu.parallel.mesh import combine_partials

    n = rng.getrandbits(128) | (1 << 127) | 1
    for modulus in (n, n * n):  # MultAll-style (n) and SumAll-style (n^2)
        ps = [rng.randrange(1, modulus) for _ in range(3)]
        want = 1
        for p in ps:
            want = want * p % modulus
        assert combine_partials(ps, modulus) == want
        # identity partials interleaved anywhere leave the result unchanged
        assert combine_partials([1] + ps[:1] + [1, 1] + ps[1:], modulus) == want
        assert combine_partials([1, 1, 1], modulus) == 1


@pytest.mark.parametrize("parts", [2, 3, 5, 7])
def test_combine_partials_matches_flat_fold_any_partition(parts):
    """Partition-independence: however K operands split across shards,
    the combined per-shard partials equal the flat fold bit-for-bit —
    the invariant the sharded SumAll/MatVec equality tests build on."""
    from dds_tpu.parallel.mesh import combine_partials

    n = rng.getrandbits(256) | (1 << 255) | 1
    ops = [rng.randrange(1, n) for _ in range(23)]
    flat = 1
    for o in ops:
        flat = flat * o % n
    cuts = sorted(rng.sample(range(1, len(ops)), parts - 1))
    partials = []
    for lo, hi in zip([0] + cuts, cuts + [len(ops)]):
        p = 1
        for o in ops[lo:hi]:
            p = p * o % n
        partials.append(p)
    assert combine_partials(partials, n) == flat


# ------------------------------------------- fast kernels under the mesh

@pytest.mark.parametrize("kernel", ["v1", "v2"])
def test_sharded_reduce_runs_fast_kernels(kernel):
    """The shard-local fold must run the v1/v2 Pallas kernels (interpret
    mode on the CPU fabric) and still match python ints — the multi-chip
    path keeps single-chip kernel speed (VERDICT r4 #1)."""
    n = rng.getrandbits(512) | (1 << 511) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    cs_int = [rng.randrange(n) for _ in range(21)]
    cs = bn.ints_to_batch(cs_int, ctx.L)
    out = sharded_reduce_mul_fixed(ctx, cs, mesh, kernel=kernel)
    want = 1
    for c in cs_int:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


@pytest.mark.parametrize("kernel", ["v1", "v2"])
def test_sharded_pow_runs_fast_kernels(kernel):
    n = rng.getrandbits(256) | (1 << 255) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    exp = rng.getrandbits(48)
    bases_int = [rng.randrange(n) for _ in range(16)]
    bases = bn.ints_to_batch(bases_int, ctx.L)
    out = sharded_pow_mod(ctx, bases, _exp_to_digits(exp), mesh, kernel=kernel)
    assert bn.batch_to_ints(np.asarray(out)) == [pow(b, exp, n) for b in bases_int]


def test_sharded_ring_with_v2_kernel():
    """ppermute ring combine composes with the v2 shard-local fold."""
    n = rng.getrandbits(512) | (1 << 511) | 1
    ctx = ModCtx.make(n)
    mesh = make_mesh(8)
    cs_int = [rng.randrange(n) for _ in range(16)]
    out = sharded_reduce_mul_fixed(
        ctx, bn.ints_to_batch(cs_int, ctx.L), mesh, ring=True, kernel="v2"
    )
    want = 1
    for c in cs_int:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


def test_backend_mesh_dispatches_configured_kernel(monkeypatch):
    """TpuBackend(pallas=True, kernel=v2, mesh=...) must hand kernel='v2'
    to the sharded fold/modexp — the wiring the r4 verdict found missing."""
    from dds_tpu.models.backend import TpuBackend
    from dds_tpu.parallel import mesh as pm

    seen = []
    orig_reduce, orig_pow = pm.sharded_reduce_mul_fixed, pm.sharded_pow_mod

    def spy_reduce(*a, **k):
        seen.append(("reduce", k.get("kernel", "jnp")))
        return orig_reduce(*a, **k)

    def spy_pow(*a, **k):
        seen.append(("pow", k.get("kernel", "jnp")))
        return orig_pow(*a, **k)

    monkeypatch.setattr(pm, "sharded_reduce_mul_fixed", spy_reduce)
    monkeypatch.setattr(pm, "sharded_pow_mod", spy_pow)

    n = rng.getrandbits(256) | (1 << 255) | 1
    be = TpuBackend(pallas=True, kernel="v2", min_device_batch=0,
                    mesh=make_mesh(4))
    cs = [rng.randrange(n) for _ in range(8)]
    want = 1
    for c in cs:
        want = want * c % n
    assert be.modmul_fold(cs, n) == want
    bases = [rng.randrange(n) for _ in range(4)]
    assert be.powmod_batch(bases, 65537, n) == [pow(b, 65537, n) for b in bases]
    assert ("reduce", "v2") in seen and ("pow", "v2") in seen
    # pallas off -> portable jnp kernels under the mesh
    be_jnp = TpuBackend(pallas=False, min_device_batch=0, mesh=make_mesh(4))
    assert be_jnp.modmul_fold(cs, n) == want
    assert seen[-1] == ("reduce", "jnp")


# ----------------------------------------------------- serving-path wiring

def test_tpu_backend_folds_through_mesh(monkeypatch):
    """TpuBackend(mesh=...) routes reduce_mul_device and powmod_batch
    through the sharded kernels — the serving-path wiring of §5.7."""
    from dds_tpu.models.backend import TpuBackend
    from dds_tpu.parallel import mesh as pm

    calls = {"reduce": 0, "pow": 0}
    orig_reduce, orig_pow = pm.sharded_reduce_mul_fixed, pm.sharded_pow_mod

    def spy_reduce(*a, **k):
        calls["reduce"] += 1
        return orig_reduce(*a, **k)

    def spy_pow(*a, **k):
        calls["pow"] += 1
        return orig_pow(*a, **k)

    monkeypatch.setattr(pm, "sharded_reduce_mul_fixed", spy_reduce)
    monkeypatch.setattr(pm, "sharded_pow_mod", spy_pow)

    n = rng.getrandbits(512) | (1 << 511) | 1
    be = TpuBackend(pallas=False, min_device_batch=0, mesh=make_mesh(4))
    cs = [rng.randrange(n) for _ in range(19)]
    want = 1
    for c in cs:
        want = want * c % n
    assert be.modmul_fold(cs, n) == want
    assert calls["reduce"] == 1

    bases = [rng.randrange(n) for _ in range(7)]  # not divisible by 4: pads
    assert be.powmod_batch(bases, 65537, n) == [pow(b, 65537, n) for b in bases]
    assert calls["pow"] == 1


def test_dds_mesh_env_builds_mesh_lazily(monkeypatch):
    from dds_tpu.models.backend import TpuBackend

    monkeypatch.setenv("DDS_MESH", "4")
    be = TpuBackend(pallas=False, min_device_batch=0)
    assert be.mesh is None  # not built yet
    n = rng.getrandbits(512) | (1 << 511) | 1
    cs = [rng.randrange(n) for _ in range(8)]
    want = 1
    for c in cs:
        want = want * c % n
    assert be.modmul_fold(cs, n) == want
    assert be.mesh is not None and be.mesh.devices.size == 4
