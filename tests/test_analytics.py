"""Prism encrypted-analytics tests (ISSUE 6 acceptance surface).

Covers the PC-MM pipeline end to end: the weighted-fold kernel against
python-int modexp (including the full-width exponents the n-|w| negative
encoding produces), backend parity (cpu / tpu / native, device and host
crossover paths), the Paillier weight-encoding primitive, the REST route
family decrypting to the plaintext W @ x (negative weights and zero rows
included), bit-for-bit S=4 vs S=1 sharded equality over identical
ciphertexts, a WrongShard fence healing mid-MatVec under a seeded
ChaosNet schedule, the request limits / 4xx paths, the /metrics + /slo
surface for the new routes, the DDS_ANALYTICS_MAX_ROWS validation, and
the sentry --check contract for `analytics matvec` records.

Everything here runs without the `cryptography` package: keys are
512-bit or smaller, which `PaillierKey.generate` serves from the local
prime generator (the PR 1 fallback), and the routes themselves touch
public parameters only.
"""

import asyncio
import contextlib
import json
import random

import pytest

from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.models.backend import get_backend
from dds_tpu.models.paillier import PaillierKey
from dds_tpu.ops.foldmany import fold_weighted

pytestmark = pytest.mark.analytics

rng = random.Random(41)
KEY = PaillierKey.generate(512)  # local-prime path: no `cryptography` needed
PK = KEY.public


def _want_rows(cs, weights, modulus):
    out = []
    for row in weights:
        acc = 1
        for c, w in zip(cs, row):
            acc = acc * pow(c, w, modulus) % modulus
        out.append(acc)
    return out


# ------------------------------------------------------------------ kernel


@pytest.mark.parametrize("kernel", ["jnp", "v2"])
def test_fold_weighted_matches_int(kernel):
    n = rng.getrandbits(256) | (1 << 255) | 1
    cs = [rng.randrange(1, n) for _ in range(5)]  # non-power-of-two K
    weights = [
        [rng.randrange(0, 1 << 20) for _ in range(5)] for _ in range(3)
    ]
    weights[1][2] = 0           # zero weight gathers the identity entry
    weights[2] = [0] * 5        # an all-zero row must come back as 1
    got = fold_weighted(cs, weights, n, kernel=kernel)
    assert got == _want_rows(cs, weights, n)


def test_fold_weighted_full_width_negative_encoding():
    """The n-|w| encoding makes negative weights full-n-width exponents;
    the digit ladder must stay exact across hundreds of scan steps."""
    n = rng.getrandbits(256) | (1 << 255) | 1
    cs = [rng.randrange(1, n) for _ in range(2)]
    weights = [[n - 5, 3]]
    assert fold_weighted(cs, weights, n) == _want_rows(cs, weights, n)


def test_fold_weighted_rejects_bad_shapes():
    n = (1 << 127) - 1
    with pytest.raises(ValueError):
        fold_weighted([], [[1]], n)
    with pytest.raises(ValueError):
        fold_weighted([3, 5], [[1]], n)       # row narrower than operands
    with pytest.raises(ValueError):
        fold_weighted([3], [[-1]], n)         # unencoded negative
    with pytest.raises(ValueError):
        fold_weighted([3], [[n]], n)          # exponent >= modulus


def test_backend_matvec_parity():
    """cpu / native / tpu (device path AND host-crossover path) all agree
    with python ints over one input set."""
    n2 = PK.nsquare
    cs = [PK.encrypt_fast(rng.randrange(1 << 20)) for _ in range(4)]
    enc = PK.matvec_encode(
        [[rng.randrange(-9, 9) for _ in range(4)] for _ in range(3)]
    )
    want = _want_rows(cs, enc, n2)
    assert get_backend("cpu").matvec(cs, enc, n2) == want
    assert get_backend("native").matvec(cs, enc, n2) == want
    from dds_tpu.models.backend import TpuBackend

    assert TpuBackend(pallas=False, min_device_batch=0).matvec(
        cs, enc, n2) == want                      # device weighted fold
    assert TpuBackend(pallas=False, min_device_batch=10**6).matvec(
        cs, enc, n2) == want                      # below-crossover host loop


# ------------------------------------------------------------------ encoding


def test_matvec_encode_signed_and_bounds():
    n = PK.n
    enc = PK.matvec_encode([[3, -4, 0]])
    assert enc == [[3, n - 4, 0]]
    with pytest.raises(ValueError):
        PK.matvec_encode([[n]])
    with pytest.raises(ValueError):
        PK.matvec_encode([[-n]])
    # the host reference composes with the encoding: decrypt == W @ x
    xs = [rng.randrange(1 << 16) for _ in range(3)]
    cs = [PK.encrypt_fast(x) for x in xs]
    W = [[2, -3, 1], [0, 0, 0]]
    out = PK.matvec(cs, PK.matvec_encode(W))
    got = [KEY.to_signed(KEY.decrypt(c)) for c in out]
    assert got == [sum(w * x for w, x in zip(row, xs)) for row in W]


def test_flags_analytics_max_rows(monkeypatch):
    from dds_tpu.ops.flags import analytics_max_rows

    monkeypatch.delenv("DDS_ANALYTICS_MAX_ROWS", raising=False)
    assert analytics_max_rows() == 256
    assert analytics_max_rows(17) == 17
    monkeypatch.setenv("DDS_ANALYTICS_MAX_ROWS", "64")
    assert analytics_max_rows(17) == 64          # env wins over config
    for bad in ("zero", "0", "-3", "9999999"):
        monkeypatch.setenv("DDS_ANALYTICS_MAX_ROWS", bad)
        with pytest.raises(ValueError):
            analytics_max_rows()
    monkeypatch.delenv("DDS_ANALYTICS_MAX_ROWS", raising=False)
    with pytest.raises(ValueError):
        analytics_max_rows(0)                    # config value validated too


# ------------------------------------------------------------------ REST


@contextlib.asynccontextmanager
async def rest_stack(n=4, quorum=3, **proxy_kw):
    net = InMemoryNet()
    addrs = [f"replica-{i}" for i in range(n)]
    replicas = {
        a: BFTABDNode(a, addrs, "supervisor", net,
                      ReplicaConfig(quorum_size=quorum))
        for a in addrs
    }
    abd = AbdClient("proxy-0", net, addrs, AbdClientConfig(quorum_size=quorum))
    server = DDSRestServer(
        abd, ProxyConfig(host="127.0.0.1", port=0, **proxy_kw)
    )
    await server.start()
    try:
        yield server, replicas
    finally:
        await server.stop()


async def call(server, method, target, obj=None, raw=None):
    body = raw if raw is not None else (
        json.dumps(obj).encode() if obj is not None else None
    )
    return await http_request(
        "127.0.0.1", server.cfg.port, method, target, body, timeout=30.0
    )


async def _put_rows(server, xs):
    """Store one single-column encrypted record per value; returns
    key -> plaintext for all of them."""
    keymap = {}
    for x in xs:
        st, key = await call(
            server, "POST", "/PutSet", {"contents": [str(PK.encrypt_fast(x))]}
        )
        assert st == 200
        keymap[key.decode()] = x
    return keymap


def test_rest_matvec_decrypts_to_plaintext_matmul():
    async def go():
        async with rest_stack() as (server, _):
            xs = [rng.randrange(1 << 20) for _ in range(5)]
            keymap = await _put_rows(server, xs)
            W = [[rng.randrange(-50, 50) for _ in range(5)] for _ in range(3)]
            W[2] = [0] * 5                       # zero row -> Enc(0)
            st, body = await call(
                server, "POST", f"/MatVec?position=0&nsqr={PK.nsquare}",
                {"weights": W},
            )
            assert st == 200
            d = json.loads(body)
            assert d["keys"] == sorted(keymap)   # column order is echoed
            col = [keymap[k] for k in d["keys"]]
            got = [KEY.to_signed(KEY.decrypt(int(c))) for c in d["result"]]
            assert got == [sum(w * x for w, x in zip(row, col)) for row in W]

            # WeightedSum = the one-row special case
            row = [1, -1, 2, 0, -3]
            st, body = await call(
                server, "POST", f"/WeightedSum?position=0&nsqr={PK.nsquare}",
                {"weights": row},
            )
            assert st == 200
            d = json.loads(body)
            got = KEY.to_signed(KEY.decrypt(int(d["result"])))
            assert got == sum(w * x for w, x in zip(row, col))

    asyncio.run(go())


def test_rest_groupby_sum_selector_rollups():
    async def go():
        async with rest_stack() as (server, _):
            xs = [rng.randrange(1 << 20) for _ in range(6)]
            keymap = await _put_rows(server, xs)
            keys = sorted(keymap)
            groups = {"evens": keys[0::2], "odds": keys[1::2]}
            st, body = await call(
                server, "POST", f"/GroupBySum?position=0&nsqr={PK.nsquare}",
                {"groups": groups},
            )
            assert st == 200
            result = json.loads(body)["result"]
            for label, members in groups.items():
                got = KEY.decrypt(int(result[label]))
                assert got == sum(keymap[k] for k in members)
            # a group naming an unknown key is a bad request, not a
            # silently-smaller rollup
            st, body = await call(
                server, "POST", f"/GroupBySum?position=0&nsqr={PK.nsquare}",
                {"groups": {"g": [keys[0], "NOT-A-KEY"]}},
            )
            assert st == 400 and b"unknown record key" in body

    asyncio.run(go())


def test_rest_analytics_limits_and_4xx():
    async def go():
        async with rest_stack(
            analytics_max_rows=2, analytics_max_request_bytes=4096
        ) as (server, _):
            nsqr = PK.nsquare
            # no stored records yet -> 404 (like SumAll over an empty store)
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={nsqr}",
                {"weights": [[1]]},
            )
            assert st == 404
            keymap = await _put_rows(server, [5, 7])
            ok = [[1, 2]]
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={nsqr}",
                {"weights": ok},
            )
            assert st == 200
            # row cap (the validated DDS_ANALYTICS_MAX_ROWS knob)
            st, body = await call(
                server, "POST", f"/MatVec?position=0&nsqr={nsqr}",
                {"weights": [[1, 2]] * 3},
            )
            assert st == 400 and b"row cap" in body
            # width mismatch against the stored operand columns
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={nsqr}",
                {"weights": [[1, 2, 3]]},
            )
            assert st == 400
            # non-integer weights (bool is NOT 1/0 here)
            for bad in ([[True, 2]], [["x", 2]], [[1.5, 2]], "nope", {}):
                st, _ = await call(
                    server, "POST", f"/MatVec?position=0&nsqr={nsqr}",
                    {"weights": bad} if not isinstance(bad, str) else bad,
                )
                assert st == 400, bad
            # nsqr must be a perfect square (a Paillier n^2)
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={nsqr + 1}",
                {"weights": ok},
            )
            assert st == 400
            # WeightedSum takes a flat row, not a matrix
            st, _ = await call(
                server, "POST", f"/WeightedSum?position=0&nsqr={nsqr}",
                {"weights": [[1, 2]]},
            )
            assert st == 400
            # oversize body -> 413 before JSON parsing
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={nsqr}",
                raw=b"x" * 5000,
            )
            assert st == 413
            # negative position never indexes from the end
            st, _ = await call(
                server, "POST", f"/MatVec?position=-1&nsqr={nsqr}",
                {"weights": ok},
            )
            assert st == 400

        # routes vanish when the plane is disabled
        async with rest_stack(analytics_enabled=False) as (server, _):
            await _put_rows(server, [5])
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={PK.nsquare}",
                {"weights": [[1]]},
            )
            assert st == 404

    asyncio.run(go())


def test_rest_analytics_metrics_and_slo_surface():
    async def go():
        async with rest_stack() as (server, _):
            await _put_rows(server, [3, 9])
            st, _ = await call(
                server, "POST", f"/MatVec?position=0&nsqr={PK.nsquare}",
                {"weights": [[1, 1]]},
            )
            assert st == 200
            st, body = await call(server, "GET", "/metrics")
            text = body.decode()
            for fam in ("dds_analytics_requests_total",
                        "dds_analytics_rows",
                        "dds_analytics_matvec_seconds"):
                assert fam in text, fam
            assert 'route="MatVec"' in text
            st, body = await call(server, "GET", "/slo")
            assert st == 200
            assert "MatVec" in json.loads(body)["slo"]["routes"]

    asyncio.run(go())


# ------------------------------------------------------------------ sharded


def _constellation(S, net=None, seed=3, **kw):
    from dds_tpu.shard import build_constellation

    net = net or InMemoryNet()
    kw.setdefault("n_active", 4)
    kw.setdefault("n_sentinent", 0)
    kw.setdefault("quorum", 3)
    return build_constellation(net, shard_count=S, vnodes_per_group=8,
                               seed=seed, **kw), net


def test_sharded_matvec_bit_for_bit_s4_vs_s1():
    """The sharded scatter-gather MatVec must be BIT-identical to the
    single-group evaluation over the same ciphertexts: shards share one
    Paillier modulus and the row product is associative/commutative over
    any column partition."""
    xs = [rng.randrange(1 << 20) for _ in range(6)]
    rows = [[str(PK.encrypt_fast(x))] for x in xs]  # ONE encryption, both runs
    W = [[rng.randrange(-20, 20) for _ in range(6)] for _ in range(3)]

    async def serve(S):
        const, _ = _constellation(S)
        server = DDSRestServer(const.router, ProxyConfig(port=0))
        await server.start()
        try:
            for row in rows:
                st, _ = await http_request(
                    "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                    json.dumps({"contents": row}).encode(), timeout=10.0,
                )
                assert st == 200
            if S > 1:  # the sample must genuinely span groups
                assert len(const.router.partition_keys(
                    sorted(server.stored_keys))) > 1
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "POST",
                f"/MatVec?position=0&nsqr={PK.nsquare}",
                json.dumps({"weights": W}).encode(), timeout=60.0,
            )
            assert st == 200
            return json.loads(body)
        finally:
            await server.stop()
            await const.stop()

    async def go():
        single = await serve(1)
        sharded = await serve(4)
        assert sharded == single                  # bit-for-bit, keys included
        # and it decrypts to the plaintext matmul
        from dds_tpu.utils import sigs

        bykey = {sigs.key_from_set(row): x for row, x in zip(rows, xs)}
        xcol = [bykey[k] for k in single["keys"]]
        got = [KEY.to_signed(KEY.decrypt(int(c))) for c in single["result"]]
        assert got == [sum(w * x for w, x in zip(r, xcol)) for r in W]

    asyncio.run(go())


@pytest.mark.chaos
def test_wrong_shard_retry_mid_matvec_chaosnet():
    """A seeded ChaosNet schedule with delivery jitter, plus an epoch+1
    fence installed on one group while a MatVec is in flight: the fenced
    quorum round surfaces WrongShardError, the proxy's deadline-budgeted
    retry spins, and once the fence rolls back (the abort path's
    force-install) the SAME request completes correctly — no 5xx, no
    misroute, wrong-shard retries visible in metrics."""
    from dds_tpu.core.chaos import ChaosNet, LinkFaults
    from dds_tpu.obs.metrics import metrics
    from dds_tpu.shard.shardmap import ShardMap

    async def go():
        net = ChaosNet(InMemoryNet(), seed=606)
        net.default_faults = LinkFaults(delay=0.002, jitter=0.004)
        const, _ = _constellation(2, net=net, seed=9)
        server = DDSRestServer(const.router, ProxyConfig(port=0))
        await server.start()
        try:
            xs = []
            while True:  # store until the sample spans BOTH groups
                x = rng.randrange(1 << 16)
                st, _ = await http_request(
                    "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                    json.dumps(
                        {"contents": [str(PK.encrypt_fast(x))]}
                    ).encode(), timeout=10.0,
                )
                assert st == 200
                xs.append(x)
                if len(xs) >= 4 and len(const.router.partition_keys(
                        sorted(server.stored_keys))) == 2:
                    break
                assert len(xs) < 32  # 2^-31-unlucky, not a bug
            before = metrics.value(
                "dds_wrong_shard_retries_total", shard="s1") or 0
            old = const.manager.current()
            secret = const.secret
            # freeze s1 out of the whole keyspace under epoch+1 (the
            # router keeps serving the old map: a stale route)
            fence = ShardMap(
                old.epoch + 1, tuple((p, "s0") for p, _ in old.vnodes),
                ("s0",),
            ).sign(secret)
            const.group("s1").state.install(fence)

            async def heal():
                await asyncio.sleep(0.15)
                const.group("s1").state.install(old, force=True)

            matvec = http_request(
                "127.0.0.1", server.cfg.port, "POST",
                f"/MatVec?position=0&nsqr={PK.nsquare}",
                json.dumps({"weights": [[1] * len(xs)]}).encode(),
                timeout=30.0,
            )
            (st, body), _ = await asyncio.gather(matvec, heal())
            assert st == 200
            got = KEY.decrypt(int(json.loads(body)["result"][0]))
            assert got == sum(xs)
            after = metrics.value(
                "dds_wrong_shard_retries_total", shard="s1") or 0
            assert after > before  # the fence really interposed mid-request
        finally:
            await server.stop()
            await const.stop()
            net.heal_all()

    asyncio.run(go())


# ------------------------------------------------------------------ sentry


def test_sentry_check_parses_analytics_records(tmp_path):
    from benchmarks.sentry import _check_analytics_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "analytics matvec: Enc(W·x) rows/s @ 2x8, 256-bit",
        "value": 100.0, "unit": "rows/s", "vs_baseline": 2.0,
        "detail": {"rows": 2, "cols": 8, "server_ms": 1.0, "client_ms": 2.0},
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_analytics_records(str(tmp_path)) == {"rows": 1}
    bad = dict(good, detail={"rows": 2})         # missing timings
    (bench / "results.json").write_text(json.dumps([good, bad]))
    with pytest.raises(ValueError):
        _check_analytics_records(str(tmp_path))
    # other record families are ignored by this checker
    (bench / "results.json").write_text(
        json.dumps([{"metric": "shard scaling: whatever", "value": -1}])
    )
    assert _check_analytics_records(str(tmp_path)) == {"rows": 0}
