"""ChaosNet fabric tests: determinism, each fault type, partitions,
Nemesis attacks, breaker-driven recovery, and REST graceful degradation
(503 + Retry-After under a full partition, service resumed after heal
without a restart).

Every schedule is seeded and short-interval — wall-clock sleeps stay in
the tens of milliseconds so the suite fits the tier-1 budget."""

import asyncio
import json
import time

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request, http_request_full
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.malicious.trudy import Nemesis, parse_attack
from dds_tpu.utils.retry import CircuitBreaker

pytestmark = pytest.mark.chaos


def run(coro):
    return asyncio.run(coro)


async def _scripted_sends(seed):
    """A fixed send sequence through a faulty fabric; returns the trace."""
    net = ChaosNet(InMemoryNet(), seed=seed)
    net.default_faults = LinkFaults(
        drop=0.2, delay=0.001, jitter=0.002, duplicate=0.2, reorder=0.2,
        corrupt=0.2,
    )
    got = []

    async def handler(sender, msg):
        got.append((sender, msg))

    net.register("sink", handler)
    for i in range(40):
        net.send(f"src-{i % 3}", "sink", M.ReadTag(f"k{i}", i))
    await net.quiesce()
    return list(net.trace), got


# ------------------------------------------------------------- determinism


def test_same_seed_reproduces_identical_fault_trace():
    t1, _ = run(_scripted_sends(1234))
    t2, _ = run(_scripted_sends(1234))
    assert t1 == t2
    assert len(t1) > 0  # the schedule actually injected faults


def test_different_seed_changes_the_fault_trace():
    t1, _ = run(_scripted_sends(1234))
    t3, _ = run(_scripted_sends(4321))
    assert t1 != t3


# --------------------------------------------------------- individual faults


def _sink_net(seed=0):
    net = ChaosNet(InMemoryNet(), seed=seed)
    got = []

    async def handler(sender, msg):
        got.append(msg)

    net.register("sink", handler)
    return net, got


def test_drop_fault_loses_the_message():
    async def go():
        net, got = _sink_net()
        net.set_link("a", "sink", LinkFaults(drop=1.0))
        net.send("a", "sink", M.ReadTag("k", 1))
        net.send("b", "sink", M.ReadTag("k", 2))  # unfaulted link flows
        await net.quiesce()
        assert [m.nonce for m in got] == [2]
        assert any(e[4] == "drop" for e in net.trace)

    run(go())


def test_delay_fault_defers_but_delivers():
    async def go():
        net, got = _sink_net()
        net.set_dest("sink", LinkFaults(delay=0.03))
        t0 = time.monotonic()
        net.send("a", "sink", M.ReadTag("k", 1))
        assert got == []  # not yet
        await net.quiesce()
        assert [m.nonce for m in got] == [1]
        assert time.monotonic() - t0 >= 0.025

    run(go())


def test_duplicate_fault_delivers_twice():
    async def go():
        net, got = _sink_net()
        net.set_link("a", "sink", LinkFaults(duplicate=1.0))
        net.send("a", "sink", M.ReadTag("k", 7))
        await net.quiesce()
        assert [m.nonce for m in got] == [7, 7]

    run(go())


def test_reorder_fault_swaps_consecutive_messages():
    async def go():
        net, got = _sink_net()
        net.set_link("a", "sink", LinkFaults(reorder=1.0))
        net.send("a", "sink", M.ReadTag("k", 1))  # parked
        net.send("a", "sink", M.ReadTag("k", 2))  # overtakes
        await net.quiesce()
        assert [m.nonce for m in got] == [2, 1]

    run(go())


def test_parked_message_flushes_on_a_quiet_link():
    async def go():
        net, got = _sink_net()
        net.set_link("a", "sink", LinkFaults(reorder=1.0))
        net.send("a", "sink", M.ReadTag("k", 1))  # parked, nothing follows
        await net.quiesce()  # quiesce releases it rather than stranding it
        assert [m.nonce for m in got] == [1]

    run(go())


def test_corrupt_fault_mutates_or_drops_never_passes_verbatim():
    async def go():
        net, got = _sink_net(seed=3)
        net.set_link("a", "sink", LinkFaults(corrupt=1.0))
        sent = [M.ReadTag(f"key-{i}", i) for i in range(20)]
        for m in sent:
            net.send("a", "sink", m)
        await net.quiesce()
        assert len(got) < len(sent)  # some corruptions were undecodable
        for m in got:
            assert m not in sent  # every survivor is a mutated payload

    run(go())


# ---------------------------------------------------------------- partitions


def test_symmetric_partition_blocks_both_directions_and_heals():
    async def go():
        net = ChaosNet(InMemoryNet(), seed=0)
        boxes = {"a": [], "b": []}

        async def make(name):
            async def h(sender, msg):
                boxes[name].append(msg.nonce)
            net.register(name, h)

        await make("a")
        await make("b")
        p = net.partition(["a"])
        net.send("a", "b", M.ReadTag("k", 1))
        net.send("b", "a", M.ReadTag("k", 2))
        await net.quiesce()
        assert boxes == {"a": [], "b": []}
        p.heal()
        net.send("a", "b", M.ReadTag("k", 3))
        net.send("b", "a", M.ReadTag("k", 4))
        await net.quiesce()
        assert boxes == {"a": [4], "b": [3]}

    run(go())


def test_asymmetric_partition_blocks_one_direction_only():
    async def go():
        net = ChaosNet(InMemoryNet(), seed=0)
        boxes = {"a": [], "b": []}
        for name in ("a", "b"):
            async def h(sender, msg, _name=name):
                boxes[_name].append(msg.nonce)
            net.register(name, h)
        net.partition(["a"], ["b"], symmetric=False)
        net.send("a", "b", M.ReadTag("k", 1))  # a -> b cut
        net.send("b", "a", M.ReadTag("k", 2))  # b -> a flows
        await net.quiesce()
        assert boxes == {"a": [2], "b": []}

    run(go())


def test_timed_partition_heals_itself():
    async def go():
        net = ChaosNet(InMemoryNet(), seed=0)
        got = []

        async def h(sender, msg):
            got.append(msg.nonce)

        net.register("b", h)
        net.partition(["a"], duration=0.05)
        net.send("a", "b", M.ReadTag("k", 1))
        await asyncio.sleep(0.08)
        net.send("a", "b", M.ReadTag("k", 2))
        await net.quiesce()
        assert got == [2]

    run(go())


def test_partition_matches_bare_names_on_hostport_addresses():
    p = ChaosNet(InMemoryNet()).partition(["replica-1"])
    assert p.blocks("10.0.0.1:2552/replica-1", "10.0.0.2:2552/replica-2")
    assert p.blocks("10.0.0.2:2552/replica-2", "10.0.0.1:2552/replica-1")
    assert not p.blocks("10.0.0.2:2552/replica-2", "10.0.0.2:2552/replica-3")


# ------------------------------------------------------------------- Nemesis


def test_parse_attack_knows_the_nemesis_attacks():
    for name in ("partition", "delay", "flood", "heal"):
        assert parse_attack(name).value == name
    with pytest.raises(ValueError):
        parse_attack("emp")


def test_nemesis_partition_delay_flood_heal():
    async def go():
        import random

        net = ChaosNet(InMemoryNet(), seed=0)
        flood_seen = []

        async def h(sender, msg):
            flood_seen.append(msg)

        net.register("replica-0", h)
        nem = Nemesis(net, ["replica-0"], max_faults=1,
                      rng=random.Random(1), delay=0.01, flood_messages=5)

        assert nem.trigger("partition") == ["replica-0"]
        assert net.partitions and net.partitions[0].blocks("replica-0", "x")

        nem.trigger("delay")
        assert net.links["replica-0"].delay == 0.01

        nem.trigger("flood")
        await net.quiesce()
        # flood arrives (the partition blocks replica-0's traffic, but
        # trudy is outside the partitioned group on the trudy->replica link?
        # no: replica-0 is isolated, so the junk is CUT — heal first)
        nem.trigger("heal")
        assert not net.partitions and not net.links
        nem.trigger("flood")
        await net.quiesce()
        assert len(flood_seen) == 5
        assert all(isinstance(m, M.Envelope) for m in flood_seen)

    run(go())


def test_nemesis_refuses_network_attacks_on_plain_transport():
    import random

    nem = Nemesis(InMemoryNet(), ["r0"], rng=random.Random(0))
    with pytest.raises(TypeError):
        nem.trigger("partition")


# --------------------------------------- breaker integration (quorum client)


def test_timeouts_trip_breaker_not_permanent_suspicion():
    """A partitioned coordinator opens its circuit breaker (self-healing)
    but earns NO permanent suspicion strikes — after heal + reset the same
    replica coordinates again without any membership reset."""

    async def go():
        from tests.test_core import Cluster

        net = ChaosNet(InMemoryNet(), seed=9)
        c = Cluster(net=net)
        c.client.cfg.request_timeout = 0.1
        c.client.cfg.breaker_reset = 0.15
        c.client.replicas.reset(["replica-0"])  # force the coordinator pick
        p = net.partition(["proxy-0"])
        for _ in range(3):
            with pytest.raises(asyncio.TimeoutError):
                await c.client.fetch_set("K")
        assert c.client.breakers["replica-0"].state == CircuitBreaker.OPEN
        assert c.client.replicas._strikes["replica-0"] == 0  # no strikes
        assert c.client.replicas.get_trusted() == ["replica-0"]  # still member
        p.heal()
        await asyncio.sleep(0.2)  # past breaker_reset -> half-open probe
        assert await c.client.fetch_set("K") is None  # quorum works again
        assert c.client.breakers["replica-0"].state == CircuitBreaker.CLOSED

    run(go())


# ------------------------------------- REST graceful degradation end-to-end


async def _chaos_rest_stack():
    net = ChaosNet(InMemoryNet(), seed=77)
    rcfg = ReplicaConfig(quorum_size=3)
    addrs = [f"replica-{i}" for i in range(4)]
    replicas = {a: BFTABDNode(a, addrs, "supervisor", net, rcfg) for a in addrs}
    abd = AbdClient(
        "proxy-0", net, addrs,
        AbdClientConfig(request_timeout=0.12, quorum_size=3,
                        breaker_reset=0.15),
    )
    server = DDSRestServer(
        abd,
        ProxyConfig(
            host="127.0.0.1", port=0, request_budget=0.8,
            retry_backoff=0.02, retry_max_delay=0.1, retry_after_hint=1.0,
        ),
    )
    await server.start()
    return net, server, replicas


def test_rest_returns_503_with_retry_after_under_full_partition_then_heals():
    """Acceptance: a GET/PUT issued while every replica is unreachable
    returns 503 + Retry-After within the configured budget (no unbounded
    hang), and the SAME server serves again after heal — no restart."""

    async def go():
        net, server, _ = await _chaos_rest_stack()
        try:
            # healthy baseline: store a row
            status, _, body = await http_request_full(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": ["a", "b"]}).encode(),
            )
            assert status == 200
            key = body.decode()

            # cut the proxy off from EVERY replica
            p = net.partition(["proxy-0"])

            for method, target, payload in (
                ("GET", f"/GetSet/{key}", None),
                ("POST", "/PutSet", json.dumps({"contents": ["x"]}).encode()),
            ):
                t0 = time.monotonic()
                status, headers, _ = await http_request_full(
                    "127.0.0.1", server.cfg.port, method, target, payload,
                )
                elapsed = time.monotonic() - t0
                assert status == 503, (method, status)
                assert int(headers["retry-after"]) >= 1
                # bounded by the budget (plus scheduling slack), not hanging
                assert elapsed < 3 * server.cfg.request_budget, elapsed

            # degraded /health while partitioned. Organic traffic spreads
            # failures over random coordinators, so drive every breaker to
            # its threshold deterministically before probing the route.
            for r in server.abd.replicas.get_all():
                for _ in range(server.abd.cfg.breaker_threshold):
                    server.abd._breaker(r).record_failure()
            status, headers, body = await http_request_full(
                "127.0.0.1", server.cfg.port, "GET", "/health",
            )
            health = json.loads(body)
            assert status == 503 and health["status"] == "degraded"
            assert health["reachable_replicas"] < health["quorum_size"]
            assert "retry-after" in headers

            # heal; after the breaker reset the SAME server serves again
            p.heal()
            await asyncio.sleep(0.2)
            status, _, body = await http_request_full(
                "127.0.0.1", server.cfg.port, "GET", f"/GetSet/{key}",
            )
            assert status == 200
            assert json.loads(body)["contents"] == ["a", "b"]

            status, _, body = await http_request_full(
                "127.0.0.1", server.cfg.port, "GET", "/health",
            )
            health = json.loads(body)
            assert status == 200 and health["status"] == "ok"
            assert health["active_replicas"] == 4
            assert all(s == "closed" for s in health["breakers"].values()) or \
                health["reachable_replicas"] >= health["quorum_size"]
        finally:
            await server.stop()

    run(go())


def test_health_route_reports_ok_on_a_healthy_stack():
    async def go():
        net, server, _ = await _chaos_rest_stack()
        try:
            status, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/health"
            )
            health = json.loads(body)
            assert status == 200
            assert health["status"] == "ok"
            assert health["active_replicas"] == 4
            assert health["quorum_size"] == 3
            assert health["breakers"] == {}  # no failures yet
        finally:
            await server.stop()

    run(go())
