"""Sanctum secret-material-plane tests: fused CRT decrypt parity (device
path on the CPU/interpret twin, straddling min_batch), the key-hygiene
regression the plane exists for (no secret modulus in ModCtx.make's
cache, zero new persistent compile-cache entries, native consts cache
untouched), key-lifetime gc/weakref zeroization, the pinned
(-n/2, n/2] signed boundary, the secret_lint static audit (clean
repo-wide + the original decrypt_batch(backend=...) fixture caught), and
the sentry `decrypt throughput` record contract.
"""

import gc
import json
import random
import weakref

import pytest

from dds_tpu.models.paillier import PaillierKey
from dds_tpu.models.primes import rsa_primes
from dds_tpu.sanctum import (
    HostCrtPlan,
    SecretBackend,
    is_secret_backend,
    plan_for,
)

pytestmark = pytest.mark.sanctum

rng = random.Random(0x5A9C)


def _fresh_key(bits: int = 512) -> PaillierKey:
    p, q = rsa_primes(bits)
    return PaillierKey(n=p * q, p=p, q=q)


# one shared key for the read-only tests; lifetime tests mint their own
KEY = _fresh_key()
PK = KEY.public


def _cts(key, ms):
    pk = key.public
    return [pk.encrypt(m) for m in ms]


# --------------------------------------------------------------- parity


def test_device_host_parity_straddling_min_batch():
    """Bit-for-bit: the fused two-leg device dispatch (running on the
    forced-CPU jax backend — the interpret twin of the TPU path, as for
    every kernel test in this suite) equals the per-op host reference at
    batch sizes on both sides of min_batch, including the sizes where
    decrypt_batch routes below the device crossover."""
    dev = SecretBackend(device=True)
    for size in (1, 3, 15, 16, 17, 33):
        ms = [rng.randrange(KEY.n) for _ in range(size)]
        cts = _cts(KEY, ms)
        want = [KEY.decrypt(c) for c in cts]            # per-op host ref
        assert want == ms
        # through the public API, straddling min_batch=16
        assert KEY.decrypt_batch(cts, backend=dev, min_batch=16) == ms
        # the device plan itself, regardless of crossover
        assert plan_for(KEY, dev).decrypt_batch(cts) == ms


def test_device_plan_chunking_parity():
    """Batches wider than the dispatch chunk split across dispatches and
    still match the host reference exactly."""
    dev = SecretBackend(device=True, chunk=4)
    ms = [rng.randrange(KEY.n) for _ in range(11)]
    cts = _cts(KEY, ms)
    assert plan_for(KEY, dev).decrypt_batch(cts) == ms


def test_secret_backend_surface():
    assert is_secret_backend(SecretBackend())
    assert is_secret_backend(SecretBackend(device=True))
    assert not is_secret_backend(object())
    from dds_tpu.models.backend import get_backend

    assert not is_secret_backend(get_backend("cpu"))
    with pytest.raises(ValueError, match="chunk"):
        SecretBackend(chunk=0)


def test_secret_device_flag_validation(monkeypatch):
    from dds_tpu.ops.flags import secret_device

    monkeypatch.delenv("DDS_SECRET_DEVICE", raising=False)
    assert secret_device() is False
    assert secret_device(default=True) is True
    with pytest.raises(ValueError, match="secret-device"):
        secret_device(default="yes")            # config typo: loud
    monkeypatch.setenv("DDS_SECRET_DEVICE", "1")
    assert secret_device(default=False) is True
    monkeypatch.setenv("DDS_SECRET_DEVICE", "off")
    assert secret_device(default=True) is False
    monkeypatch.setenv("DDS_SECRET_DEVICE", "maybe")
    with pytest.raises(ValueError, match="DDS_SECRET_DEVICE"):
        secret_device()


# --------------------------------------------------------------- hygiene


def test_key_hygiene_no_secret_in_shared_caches(tmp_path):
    """THE regression test for the ADVICE.md medium finding: after a
    >= min_batch batched decrypt through the device plane, (1)
    ModCtx.make's cache gained no entry and holds nothing p/q-derived,
    (2) the persistent compile-cache dir gained ZERO entries — proven
    against a control public compile that demonstrably writes — and (3)
    the native consts cache is untouched. The per-plan jax.jit always
    compiles fresh, so without the bypass this WOULD write."""
    import jax

    from dds_tpu.ops import bignum as bn
    from dds_tpu.ops import montgomery

    try:
        from jax._src import compilation_cache as cc
    except ImportError:  # pragma: no cover - private API drift
        pytest.skip("jax private compilation_cache API unavailable")

    key = _fresh_key()
    p, q = key.p, key.q
    p2, q2 = p * p, q * q
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cc.reset_cache()
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        # control: a PUBLIC compile on a fresh modulus must write entries,
        # or this environment cannot observe the property under test
        mod = (1 << 89) - 1
        ctx = montgomery.ModCtx.make(mod)
        ctx.pow_mod(bn.ints_to_batch([3, 5, 7], ctx.L), 65537)
        control_files = sorted(f.name for f in tmp_path.iterdir())
        if not control_files:
            pytest.skip("persistent compile cache inactive on this backend")

        from dds_tpu import native

        # encrypt BEFORE snapshotting: encryption legitimately parks the
        # PUBLIC n^2 in the native consts cache; the decrypts below must
        # then add nothing at all
        ms = [rng.randrange(key.n) for _ in range(20)]
        cts = _cts(key, ms)
        native_size = (
            native._mont_consts.cache_info().currsize
            if native.available() else None
        )
        before_moduli = list(montgomery.cached_moduli())

        got = key.decrypt_batch(
            cts, backend=SecretBackend(device=True), min_batch=16
        )
        assert got == ms
        assert [key.decrypt(c) for c in cts] == ms      # host path too

        # (1) ModCtx.make: no new entry, nothing secret-derived
        after_moduli = montgomery.cached_moduli()
        assert after_moduli == before_moduli
        for m in after_moduli:
            assert m not in (p, q, p2, q2)
        # (2) persistent compile cache: zero new entries
        assert sorted(f.name for f in tmp_path.iterdir()) == control_files
        # (3) native consts cache: untouched by either decrypt path
        if native_size is not None:
            assert native._mont_consts.cache_info().currsize == native_size
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
        cc.reset_cache()


# -------------------------------------------------------------- lifetime


def test_dropped_key_leaves_no_reachable_secret_state():
    """gc-based key-lifetime hygiene: dropping the last reference to a
    PaillierKey frees its Sanctum plans and SecretModCtx twins (weakref
    liveness) AND zero-fills the host limb copies via the finalizer,
    without an explicit scrub()."""
    key = _fresh_key()
    dev = SecretBackend(device=True)
    ms = [rng.randrange(key.n) for _ in range(4)]
    assert key.decrypt_batch(_cts(key, ms), backend=dev, min_batch=1) == ms
    assert key.decrypt(_cts(key, ms[:1])[0]) == ms[0]   # host plan too
    plan = plan_for(key, dev)
    host_plan = plan_for(key)
    refs = [weakref.ref(o) for o in
            (plan, plan.ctx_p, plan.ctx_q, host_plan)]
    held_N = plan._N            # survives the plan; zeroized by close()
    held_digits = plan._digits
    assert held_N.any() and held_digits.any()
    del key, plan, host_plan
    gc.collect()
    assert all(r() is None for r in refs)
    assert not held_N.any()
    assert not held_digits.any()


def test_scrub_closes_plans_and_recovers():
    """Explicit scrub(): every plan closes (zeroized, unusable), the
    cached CRT constants drop, and the key remains usable — the next
    decrypt builds fresh plans."""
    key = _fresh_key()
    ms = [rng.randrange(key.n) for _ in range(3)]
    cts = _cts(key, ms)
    dev = SecretBackend(device=True)
    assert key.decrypt_batch(cts, backend=dev, min_batch=1) == ms
    plan = plan_for(key, dev)
    key.scrub()
    assert plan.closed
    with pytest.raises(RuntimeError, match="scrubbed"):
        plan.decrypt_batch(cts)
    assert "_crt" not in key.__dict__
    assert key.decrypt_batch(cts, backend=dev, min_batch=1) == ms
    assert plan_for(key, dev) is not plan


def test_host_plan_native_fallback_parity():
    """The host plan is bit-for-bit identical with and without the
    native consts (builtin-pow fallback) — the toolchain-less path."""
    key = _fresh_key()
    ms = [rng.randrange(key.n) for _ in range(5)]
    cts = _cts(key, ms)
    plan = HostCrtPlan(key)
    fallback = HostCrtPlan(key)
    fallback._consts_p = fallback._consts_q = None
    assert plan.decrypt_batch(cts) == fallback.decrypt_batch(cts) == ms


# --------------------------------------------------------- signed range


def test_to_signed_pins_half_open_interval():
    """(-n/2, n/2], the contract matvec_encode documents — shared by
    decrypt_signed and the analytics row decoder. Boundary values on a
    real (odd) modulus AND a contrived even one, where the old floor
    comparison read ambiguously at the exact midpoint."""
    n = KEY.n                                   # odd: n = p*q
    half_down, half_up = (n - 1) // 2, (n + 1) // 2
    assert KEY.to_signed(0) == 0
    assert KEY.to_signed(half_down) == half_down
    assert KEY.to_signed(half_up) == -half_down
    assert KEY.to_signed(n - 1) == -1
    # through decrypt_signed: the same single convention site
    enc = KEY.public.encrypt
    assert KEY.decrypt_signed(enc(half_down)) == half_down
    assert KEY.decrypt_signed(enc(-half_down)) == -half_down
    assert KEY.decrypt_signed(enc(half_up)) == -half_down
    # even-ish convention: midpoint n/2 is IN the range, so it stays +
    even = PaillierKey(n=10, p=2, q=5)
    assert even.to_signed(5) == 5
    assert even.to_signed(6) == -4
    assert [even.to_signed(m) for m in range(10)] == [
        0, 1, 2, 3, 4, 5, -4, -3, -2, -1
    ]


# ------------------------------------------------------------ static audit


def test_secret_lint_repo_clean():
    """Zero violations repo-wide: the boundary holds everywhere outside
    dds_tpu/sanctum — this is the tier-1 gate that freezes out the bug
    class."""
    from tools.secret_lint import lint_repo

    violations = lint_repo()
    assert violations == [], "\n".join(str(v) for v in violations)


ORIGINAL_PATTERN = '''
def decrypt_batch(self, cs, backend=None, min_batch=64):
    p, q, n = self.p, self.q, self.n
    hp, hq, qinv = self._crt
    p2, q2 = p * p, q * q
    cps = [c % p2 for c in cs]
    cqs = [c % q2 for c in cs]
    if backend is not None and len(cs) >= min_batch:
        xps = _chunked_powmod(backend, cps, p - 1, p2)
        xqs = _chunked_powmod(backend, cqs, q - 1, q2)
    else:
        xps = [powmod(cp, p - 1, p2) for cp in cps]
        xqs = [powmod(cq, q - 1, q2) for cq in cqs]
'''


def test_secret_lint_catches_original_pattern():
    """The fixture IS the pre-change decrypt_batch body (ADVICE.md
    medium finding): both backend legs and both host powmod legs must be
    flagged, so the lint provably catches the bug it was built for."""
    from tools.secret_lint import lint_source

    violations = lint_source(ORIGINAL_PATTERN, "fixture.py")
    sinks = sorted({v.sink for v in violations})
    assert sinks == ["_chunked_powmod", "powmod"]
    assert len(violations) == 4


def test_secret_lint_catches_cache_and_jit_flows():
    from tools.secret_lint import lint_source

    src = '''
def f(key, be):
    ctx = ModCtx.make(key.p * key.p)
    mctx = mont_mxu.MxuCtx.make(ctx2)
    lam2 = key.lam * 2
    be.powmod_batch(cs, lam2, modulus)
    jax.jit(builder)(key.q)
'''
    sinks = {v.sink for v in lint_source(src, "f.py")}
    assert "ModCtx.make" in sinks
    assert "powmod_batch" in sinks
    # jit call with a secret ARG: jax.jit(builder) itself takes no
    # tainted arg here; the outer call is not the jit sink — assert the
    # direct form instead
    sinks2 = {v.sink for v in lint_source(
        "def g(key):\n    jax.jit(fn, key.q)\n", "g.py")}
    assert sinks2 == {"jax.jit"}


# ---------------------------------------------------------------- sentry


def test_sentry_decrypt_record_contract(tmp_path):
    from benchmarks.sentry import _check_decrypt_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "decrypt throughput (CRT-Paillier, 1024-bit)",
        "value": 4200.0, "unit": "ops/s", "vs_baseline": 3.8,
        "detail": {
            "bits": 1024, "batch": 256, "per_op_ops": 1100.0,
            "batched_host_ops": 1900.0, "sanctum_device_ops": 4200.0,
            "verified": True,
        },
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_decrypt_records(str(tmp_path)) == {"rows": 1}
    bad = dict(good, detail=dict(good["detail"], verified=False))
    (bench / "results.json").write_text(json.dumps([good, bad]))
    with pytest.raises(ValueError, match="malformed decrypt-throughput"):
        _check_decrypt_records(str(tmp_path))
    bad2 = dict(good, detail={k: v for k, v in good["detail"].items()
                             if k != "per_op_ops"})
    (bench / "results.json").write_text(json.dumps([bad2]))
    with pytest.raises(ValueError, match="malformed decrypt-throughput"):
        _check_decrypt_records(str(tmp_path))
