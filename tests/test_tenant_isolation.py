"""Bastion REST-surface isolation tests: the tenant boundary end to end.

Small real stacks (InMemoryNet quorum + DDSRestServer) exercise the
edges the unit suite can't: the `x-dds-tenant` header clamp answering
typed 400s, cross-tenant key access answering typed 403s, per-tenant
aggregate/order scoping, the mixed-tenant same-modulus fold still
landing in ONE fused dispatch (isolation must not cost the batching
win), and the tenant surfaces on /health and /metrics.

The closing drill is the ISSUE's chaos acceptance: a client-side
`TenantKeyring` rotates and then crypto-shreds one tenant's keys in the
middle of live multi-tenant traffic. Other tenants stay linearizable
(their ciphertexts and homomorphic folds still decrypt to the right
plaintexts), the shredded tenant's ciphertexts become permanently
undecryptable with the typed refusal, and the Watchtower — auditing
every quorum op throughout — reports ZERO verdicts: key lifecycle is a
client-domain event, invisible to storage invariants.
"""

import asyncio
import contextlib
import json
import math

import pytest

from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.models.tenancy import TenantKeyring, TenantShredded
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.config import AdmissionConfig, DDSConfig, TenancyConfig
from dds_tpu.utils.trace import tracer

pytestmark = pytest.mark.tenancy


@contextlib.asynccontextmanager
async def tenancy_stack(acfg: AdmissionConfig | None = None, n=4, quorum=3,
                        **proxy_kw):
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig

    net = InMemoryNet()
    rcfg = ReplicaConfig(quorum_size=quorum)
    addrs = [f"replica-{i}" for i in range(n)]
    replicas = {a: BFTABDNode(a, addrs, "supervisor", net, rcfg)
                for a in addrs}
    abd = AbdClient("proxy-0", net, addrs,
                    AbdClientConfig(request_timeout=2.0, quorum_size=quorum))
    server = DDSRestServer(abd, ProxyConfig(
        host="127.0.0.1", port=0, admission=acfg,
        tenancy=TenancyConfig(enabled=True), **proxy_kw,
    ))
    await server.start()
    try:
        yield server, replicas
    finally:
        await server.stop()


async def _put(server, contents, tenant=None, expect=200):
    headers = {"x-dds-tenant": tenant} if tenant else None
    status, body = await http_request(
        "127.0.0.1", server.cfg.port, "POST", "/PutSet",
        json.dumps({"contents": contents}).encode(),
        headers=headers, timeout=10.0,
    )
    assert status == expect, body
    return body.decode()


async def _get(server, method, target, tenant=None, body=None):
    headers = {"x-dds-tenant": tenant} if tenant else None
    return await http_request(
        "127.0.0.1", server.cfg.port, method, target, body,
        headers=headers, timeout=10.0,
    )


# --------------------------------------------------- edge: the header clamp


def test_malformed_tenant_header_is_typed_400():
    async def go():
        async with tenancy_stack() as (server, _):
            before = metrics.value(
                "dds_tenant_header_rejects_total",
                reason="must match [A-Za-z0-9][A-Za-z0-9._-]*") or 0
            for bad in ("no spaces", "-lead", 'quo"te', "a" * 70):
                status, body = await _get(server, "GET", "/health",
                                          tenant=bad)
                assert status == 400
                err = json.loads(body)
                assert err["error"] == "invalid tenant header"
                assert err["reason"]
            after = metrics.value(
                "dds_tenant_header_rejects_total",
                reason="must match [A-Za-z0-9][A-Za-z0-9._-]*") or 0
            assert after == before + 3  # the length reject has its own reason

    asyncio.run(go())


def test_absent_header_is_the_default_tenant():
    async def go():
        async with tenancy_stack() as (server, _):
            key = await _put(server, ["123"])  # no header -> "default"
            status, body = await _get(server, "GET", f"/GetSet/{key}")
            assert status == 200
            assert json.loads(body)["contents"] == ["123"]
            # the explicit spelling is the same identity, not a stranger
            status, _ = await _get(server, "GET", f"/GetSet/{key}",
                                   tenant="default")
            assert status == 200

    asyncio.run(go())


# ------------------------------------------------- keyspace ownership: 403s


def test_cross_tenant_access_is_typed_403():
    async def go():
        async with tenancy_stack() as (server, _):
            key = await _put(server, ["7", "8"], tenant="alice")
            before = metrics.value("dds_tenant_denied_total",
                                   tenant="bob") or 0
            status, body = await _get(server, "GET", f"/GetSet/{key}",
                                      tenant="bob")
            assert status == 403
            err = json.loads(body)
            assert err == {"error": "cross-tenant access denied",
                           "tenant": "bob", "key": key}
            # mutations are refused the same way — a 403, not a quiet no-op
            status, _ = await _get(server, "DELETE", f"/RemoveSet/{key}",
                                   tenant="bob")
            assert status == 403
            assert (metrics.value("dds_tenant_denied_total", tenant="bob")
                    or 0) == before + 2
            # the owner is untouched by the attempts
            status, body = await _get(server, "GET", f"/GetSet/{key}",
                                      tenant="alice")
            assert status == 200
            assert json.loads(body)["contents"] == ["7", "8"]
            status, _ = await _get(server, "DELETE", f"/RemoveSet/{key}",
                                   tenant="alice")
            assert status == 200

    asyncio.run(go())


def test_aggregates_and_order_are_tenant_scoped():
    async def go():
        async with tenancy_stack() as (server, _):
            a_keys = [await _put(server, [v], tenant="alice")
                      for v in ("3", "5")]
            b_keys = [await _put(server, [v], tenant="bob")
                      for v in ("7", "11", "13")]
            # each tenant's SumAll folds ONLY its own records
            status, body = await _get(server, "GET", "/SumAll?position=0",
                                      tenant="alice")
            assert status == 200 and json.loads(body)["result"] == "8"
            status, body = await _get(server, "GET", "/SumAll?position=0",
                                      tenant="bob")
            assert status == 200 and json.loads(body)["result"] == "31"
            # the ordered keyset view is the tenant's own keys, nobody else's
            status, body = await _get(server, "GET", "/OrderLS?position=0",
                                      tenant="alice")
            assert status == 200
            assert set(json.loads(body)["keyset"]) == set(a_keys)
            status, body = await _get(server, "GET", "/OrderLS?position=0",
                                      tenant="bob")
            assert status == 200
            assert set(json.loads(body)["keyset"]) == set(b_keys)

    asyncio.run(go())


# ------------------------------- isolation must not break fold coalescing


class _FoldManyBackend:
    """Fold backend with a device-batch crossover, recording every fused
    dispatch so the test can prove mixed-tenant folds shared ONE."""

    name = "stub-foldmany"
    min_device_batch = 4  # alice(2) and bob(3) alone stay below; fused >= it

    def __init__(self):
        self.many_calls: list[list[int]] = []

    def modmul_fold(self, ops, modulus):
        out = 1
        for o in ops:
            out = out * o % modulus
        return out

    def modmul_fold_many(self, folds, modulus):
        self.many_calls.append(sorted(len(f) for f in folds))
        return [self.modmul_fold(f, modulus) for f in folds]


def test_mixed_tenant_same_modulus_folds_share_one_fused_dispatch():
    """Acceptance: tenant isolation scopes the OPERANDS, not the device
    batching — two tenants' folds over the same modulus coalesce into a
    single `modmul_fold_many` dispatch (the `_fold_pending` group key is
    the modulus alone), each receiving its own tenant-scoped result."""
    M = (1 << 64) + 13

    async def go():
        async with tenancy_stack(coalesce_window=0.05) as (server, _):
            a_vals = [3, 5]
            b_vals = [7, 11, 13]
            for v in a_vals:
                await _put(server, [str(v)], tenant="alice")
            for v in b_vals:
                await _put(server, [str(v)], tenant="bob")
            stub = server.backend = _FoldManyBackend()
            tracer.reset()
            # hold the inflight flag so BOTH folds take the coalescing
            # window (a lone first fold would dispatch directly — correct
            # in production, but here the fused path is the subject)
            server._folds_inflight += 1
            try:
                results = await asyncio.gather(
                    _get(server, "GET", f"/SumAll?position=0&nsqr={M}",
                         tenant="alice"),
                    _get(server, "GET", f"/SumAll?position=0&nsqr={M}",
                         tenant="bob"),
                )
            finally:
                server._folds_inflight -= 1
            (st_a, body_a), (st_b, body_b) = results
            assert st_a == 200 and st_b == 200
            assert json.loads(body_a)["result"] == str(math.prod(a_vals) % M)
            assert json.loads(body_b)["result"] == str(math.prod(b_vals) % M)
            # ONE fused dispatch carried both tenants' folds
            assert stub.many_calls == [[2, 3]]
            spans = [e for e in tracer.events("proxy.coalesced_fold")]
            assert len(spans) == 2
            assert all(e.meta.get("batch") == 2 for e in spans)
            assert sorted(e.meta.get("k") for e in spans) == [2, 3]

    asyncio.run(go())


# ------------------------------------------------- observability surfaces


def test_health_and_metrics_expose_tenant_surfaces():
    async def go():
        acfg = AdmissionConfig(enabled=True, eval_interval=1e9)
        async with tenancy_stack(acfg) as (server, _):
            key = await _put(server, ["1"], tenant="alice")
            await _put(server, ["2"], tenant="bob")
            await _get(server, "GET", f"/GetSet/{key}", tenant="bob")  # 403
            status, body = await _get(server, "GET", "/health")
            assert status == 200
            health = json.loads(body)
            assert health["tenants"] == {"owned_keys": 2, "shed": []}
            status, body = await _get(server, "GET", "/metrics")
            assert status == 200
            text = body.decode()
            assert 'dds_tenant_stored_keys{tenant="alice"} 1' in text
            assert 'dds_tenant_stored_keys{tenant="bob"} 1' in text
            assert "dds_tenant_denied_total" in text

    asyncio.run(go())


def test_chronoscope_attributes_usage_per_tenant():
    from dds_tpu.obs.chronoscope import chronoscope

    async def go():
        async with tenancy_stack() as (server, _):
            key = await _put(server, ["5"], tenant="alice")
            for _ in range(3):
                await _get(server, "GET", f"/GetSet/{key}", tenant="alice")
            await _get(server, "GET", "/SumAll?position=0", tenant="bob")

    was = chronoscope.enabled
    chronoscope.reset()
    chronoscope.enabled = True
    try:
        asyncio.run(go())
        usage = chronoscope.tenant_usage()
    finally:
        chronoscope.enabled = was
        chronoscope.reset()
    assert set(usage) >= {"alice", "bob"}
    # PutSet + 3 GetSets for alice; the lone aggregate for bob
    assert usage["alice"]["requests"] == 4
    assert usage["bob"]["requests"] == 1
    assert usage["alice"]["seconds"] > 0
    assert "GetSet" in usage["alice"]["top_routes"]
    assert "SumAll" in usage["bob"]["top_routes"]


# ----------------------------------------------- the chaos shred drill


def _drill_cfg(flight_dir: str) -> DDSConfig:
    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.proxy.port = 0
    cfg.recovery.enabled = False
    cfg.recovery.anti_entropy_enabled = False
    cfg.obs.audit_enabled = True  # the Watchtower rides along, armed
    cfg.obs.flight_dir = flight_dir
    cfg.tenancy.enabled = True
    return cfg


def test_shred_chaos_drill_other_tenants_linearizable_zero_verdicts(tmp_path):
    """Acceptance (chaos drill): rotate then crypto-shred one tenant's
    keys in the middle of live multi-tenant traffic. Surviving tenants'
    reads and homomorphic folds stay linearizable, the shredded tenant's
    ciphertexts — still faithfully served by the keyless server — are
    permanently undecryptable with the typed refusal, and the Watchtower
    audits the whole run to ZERO verdicts."""
    import pathlib

    from dds_tpu.obs.flight import flight
    from dds_tpu.obs.watchtower import watchtower
    from dds_tpu.run import launch

    flight_dir = str(tmp_path / "drill")
    kr = TenantKeyring(paillier_bits=512, rsa_bits=512, grace=300.0)
    plains = {"alice": [3, 14, 15], "bob": [92, 65], "victim": [35, 89, 79]}

    async def go():
        dep = await launch(_drill_cfg(flight_dir))
        server = dep.server

        stored: dict[str, list[tuple[str, int, int]]] = {}
        for tenant, values in plains.items():
            rows = []
            for m in values:
                ct, ver = kr.encrypt(tenant, m)
                key = await _put(server, [str(ct)], tenant=tenant)
                rows.append((key, ct, ver))
            stored[tenant] = rows

        async def read_back(tenant, key, want_ct):
            status, body = await _get(server, "GET", f"/GetSet/{key}",
                                      tenant=tenant)
            assert status == 200
            assert json.loads(body)["contents"] == [str(want_ct)]

        async def fold(tenant):
            n2 = kr.keys_for(tenant).psse.nsquare
            status, body = await _get(
                server, "GET", f"/SumAll?position=0&nsqr={n2}",
                tenant=tenant)
            assert status == 200
            return int(json.loads(body)["result"])

        async def churn(tenant):
            for key, ct, _ in stored[tenant]:
                await read_back(tenant, key, ct)

        # live traffic from every tenant, with the victim's key lifecycle
        # firing mid-stream: rotate (old epoch keeps decrypting inside
        # grace -> re-encrypt-on-read migrates a row), then the shred
        await asyncio.gather(churn("alice"), churn("bob"), churn("victim"))
        assert kr.rotate("victim") == 2
        k0, ct0, v0 = stored["victim"][0]
        ct_new, v_new, migrated = kr.reencrypt("victim", ct0, v0)
        assert migrated and v_new == 2
        assert kr.decrypt("victim", ct_new, v_new) == plains["victim"][0]
        await asyncio.gather(churn("alice"), churn("victim"), churn("bob"))
        assert kr.shred("victim")["epochs_scrubbed"] == 2
        await asyncio.gather(churn("alice"), churn("bob"))

        # survivors are linearizable END TO END: the served fold is the
        # homomorphic sum and still decrypts to the right plaintext
        for tenant in ("alice", "bob"):
            enc_sum = await fold(tenant)
            assert kr.decrypt(tenant, enc_sum) == sum(plains[tenant])

        # the keyless server still serves the shredded tenant's bytes —
        # deletion happened in the key domain, and it is total
        _, ct_v, v_v = stored["victim"][1]
        status, body = await _get(server, "GET",
                                  f"/GetSet/{stored['victim'][1][0]}",
                                  tenant="victim")
        assert status == 200
        assert json.loads(body)["contents"] == [str(ct_v)]
        for attempt in (lambda: kr.decrypt("victim", ct_v, v_v),
                        lambda: kr.decrypt("victim", ct_new, v_new),
                        lambda: kr.encrypt("victim", 1)):
            with pytest.raises(TenantShredded):
                attempt()

        verdicts = watchtower.verdicts()
        await dep.stop()
        return verdicts

    try:
        verdicts = asyncio.run(go())
    finally:
        flight.configure(dir="")  # launch() armed the global recorder
    assert verdicts == [], verdicts

    # the lifecycle is flight-recorded for the auditor
    index = pathlib.Path(flight_dir) / "index.jsonl"
    kinds = [json.loads(line)["kind"]
             for line in index.read_text().splitlines()]
    assert "tenant_rotate" in kinds
    assert "tenant_shred" in kinds
