"""Meridian multi-host fabric tests.

Covers the acceptance surface of the multi-host plane on REAL loopback
sockets: the role-driven TCP constellation (`[fabric]` role = all /
group:N / proxy), conditional `GET /shards` (ETag + 304 + long-poll
gossip push), a remote proxy bootstrapping the signed map and surviving
its own restart with zero operator input, cross-host live resharding
under a seeded ChaosNet schedule with a writer hammering a moving key,
trace-context propagation across TcpNet frames (one request = one span
tree), the node-key minting helper, the open-loop load generator's
coordinated-omission safety, and the sentry record contract for
`multihost load` rows.

Everything here runs over real TCP sockets. The in-tier-1 tests keep the
whole fleet inside ONE pytest process (multiple TcpNet instances on one
event loop — real frames, deterministic scheduling); the flagship
multi-OS-process test spawns actual `python -m dds_tpu.run` processes
and is additionally marked `slow` (sockets + interpreter startup make it
flaky-prone under CI load — the loopback smokes keep tier-1 coverage).
"""

import asyncio
import json
import random
import socket
import time

import pytest

from dds_tpu.core.errors import WrongShardError
from dds_tpu.fabric.deploy import initial_map, parse_role
from dds_tpu.fabric.gossip import RemoteShardManager
from dds_tpu.http.miniserver import (
    HttpServer,
    Response,
    http_request,
    http_request_full,
)
from dds_tpu.shard.shardmap import ShardMap
from dds_tpu.utils.config import DDSConfig
from tests.test_core import run

pytestmark = pytest.mark.multihost

SECRET = b"intranet-abd-secret"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def fabric_cfg(role, t_port, groups, bootstrap=(), status_port=0, *,
               count=2, audit=False):
    cfg = DDSConfig()
    cfg.shard.enabled = True
    cfg.shard.count = count
    cfg.transport.kind = "tcp"
    cfg.transport.port = t_port
    cfg.proxy.port = 0
    cfg.recovery.enabled = False
    cfg.obs.audit_enabled = audit
    cfg.fabric.role = role
    cfg.fabric.groups = dict(groups)
    cfg.fabric.bootstrap = list(bootstrap)
    cfg.fabric.status_port = status_port
    cfg.fabric.gossip_wait = 2.0
    cfg.fabric.admin_routes = True
    return cfg


async def _put(port, contents, timeout=10.0):
    status, body = await http_request(
        "127.0.0.1", port, "POST", "/PutSet",
        json.dumps({"contents": contents}).encode(), timeout=timeout,
    )
    assert status == 200, (status, body)
    return body.decode()


# ------------------------------------------------------------------- units


def test_parse_role_and_initial_map_determinism():
    assert parse_role("all") == ("all", None)
    assert parse_role("proxy") == ("proxy", None)
    assert parse_role("group:2") == ("group", "s2")
    assert parse_role("group:s7") == ("group", "s7")
    for bad in ("bogus", "group:", "groups:1", ""):
        if bad == "":
            assert parse_role(bad) == ("all", None)  # empty = default
            continue
        with pytest.raises(ValueError):
            parse_role(bad)
    cfg = DDSConfig()
    cfg.shard.count = 3
    m1, m2 = initial_map(cfg), initial_map(cfg)
    assert m1.vnodes == m2.vnodes and m1.epoch == m2.epoch == 1
    assert m1.verify(cfg.security.abd_mac_secret.encode())


def test_remote_shard_manager_verified_and_forward_only():
    m1 = ShardMap.build(["s0", "s1"], 8).sign(SECRET)
    mgr = RemoteShardManager(m1, SECRET)
    assert mgr.epoch == 1 and mgr.state == "stable"
    m2 = m1.split("s1", "s2").sign(SECRET)
    assert mgr.install(m2, state="resharding")
    assert mgr.epoch == 2 and mgr.state == "resharding"
    # redelivery and backwards epochs are ignored, forgeries raise
    assert not mgr.install(m2)
    assert not mgr.install(m1, state="stable")
    assert mgr.epoch == 2 and mgr.state == "stable"
    forged = ShardMap(m2.epoch + 1, m2.vnodes, m2.groups, b"nope")
    with pytest.raises(ValueError):
        mgr.install(forged)


# ------------------------------------------- role "all" over real sockets


def test_tcp_all_role_smoke_and_shards_conditional_get():
    """The tier-1 loopback smoke: a whole S=2 constellation over real
    TCP sockets in one process — point ops, /shards with ETag, and a
    near-free 304 freshness probe."""

    async def go():
        from dds_tpu.run import launch

        cfg = fabric_cfg("all", 0, {})
        dep = await launch(cfg)
        try:
            port = dep.server.cfg.port
            key = await _put(port, ["11", "22"])
            status, body = await http_request(
                "127.0.0.1", port, "GET", f"/GetSet/{key}", timeout=10.0)
            assert status == 200
            assert json.loads(body)["contents"] == ["11", "22"]
            status, headers, body = await http_request_full(
                "127.0.0.1", port, "GET", "/shards", timeout=5.0)
            assert status == 200 and headers.get("etag") == '"1"'
            served = ShardMap.from_wire(json.loads(body)["map"])
            assert served.verify(SECRET)
            # freshness probe: same epoch = 304, no body re-serialization
            status, headers, body = await http_request_full(
                "127.0.0.1", port, "GET", "/shards",
                headers={"If-None-Match": '"1"'}, timeout=5.0)
            assert status == 304 and body == b"" \
                and headers.get("etag") == '"1"'
            # a stale etag gets the full signed map immediately
            status, _, body = await http_request_full(
                "127.0.0.1", port, "GET", "/shards",
                headers={"If-None-Match": '"0"'}, timeout=5.0)
            assert status == 200 and json.loads(body)["map"]["epoch"] == 1
        finally:
            await dep.stop()

    run(go())


def test_shards_longpoll_returns_push_on_epoch_bump():
    """Epoch gossip is change notification, not polling: a parked
    long-poll (If-None-Match + wait) returns the NEW signed map the
    moment a live split activates, well before its wait expires."""

    async def go():
        from dds_tpu.run import launch

        cfg = fabric_cfg("all", 0, {})
        dep = await launch(cfg)
        try:
            port = dep.server.cfg.port
            await _put(port, ["1"])

            async def longpoll():
                t0 = time.monotonic()
                status, _, body = await http_request_full(
                    "127.0.0.1", port, "GET", "/shards?wait=30",
                    headers={"If-None-Match": '"1"'}, timeout=40.0)
                return status, json.loads(body), time.monotonic() - t0

            poll = asyncio.ensure_future(longpoll())
            await asyncio.sleep(0.1)
            assert not poll.done()  # parked, not busy-polling
            status, body = await http_request(
                "127.0.0.1", port, "POST", "/_reshard",
                json.dumps({"source": "s1"}).encode(), timeout=30.0)
            assert status == 200, body
            st, d, held = await asyncio.wait_for(poll, 10.0)
            assert st == 200 and d["map"]["epoch"] == 2
            assert held < 8.0  # pushed on the bump, not held to the cap
            assert ShardMap.from_wire(d["map"]).verify(SECRET)
        finally:
            await dep.stop()

    run(go())


# ----------------------------- multi-process-shaped fleet, one event loop


class _MiniFleet:
    """S=2 (+ optional standby) groups and a separate proxy, each on its
    OWN TcpNet — real loopback frames between 'processes' that happen to
    share one event loop, so tests stay deterministic and fast."""

    def __init__(self, standby=0, audit=False):
        self.t_ports = {f"s{i}": free_port() for i in range(2 + standby)}
        self.s_ports = {gid: free_port() for gid in self.t_ports}
        self.groups = {
            gid: f"127.0.0.1:{p}" for gid, p in self.t_ports.items()
        }
        self.bootstrap = [f"127.0.0.1:{p}" for p in self.s_ports.values()]
        self.audit = audit
        self.deps = {}

    async def start(self):
        from dds_tpu.run import launch

        for gid, t_port in self.t_ports.items():
            cfg = fabric_cfg(f"group:{gid[1:]}", t_port, self.groups,
                             self.bootstrap, self.s_ports[gid],
                             audit=self.audit)
            self.deps[gid] = await launch(cfg)
        await self.start_proxy("proxy")
        return self

    async def start_proxy(self, name):
        from dds_tpu.run import launch

        cfg = fabric_cfg("proxy", free_port(), self.groups, self.bootstrap,
                         audit=False)
        self.deps[name] = await launch(cfg)
        return self.deps[name]

    def proxy_port(self, name="proxy"):
        return self.deps[name].server.cfg.port

    async def stop(self):
        for dep in reversed(list(self.deps.values())):
            await dep.stop()
        self.deps.clear()


def test_remote_proxy_bootstrap_sumall_bitforbit_and_restart():
    """A separate proxy 'process' bootstraps the signed map from a group
    status listener, serves point ops and a scatter-gather SumAll
    bit-for-bit equal to the single-process result over IDENTICAL
    ciphertexts, and — killed and restarted — re-bootstraps from
    GET /shards with zero operator input."""
    from dds_tpu.http.server import DDSRestServer, ProxyConfig
    from dds_tpu.models import HEKeys

    from dds_tpu.utils import sigs

    he = HEKeys.generate(paillier_bits=512, rsa_bits=512)
    pk = he.psse.public
    vals = [7, 21, 301, 44, 5, 600]
    # ONE encryption feeds both runs (bit-for-bit comparison); blinding
    # randomizes the content-hash keys, so re-encrypt until the sample
    # provably spans both groups of the deterministic epoch-1 map
    smap = ShardMap.build(["s0", "s1"], 16)
    while True:
        rows = [[str(pk.encrypt(v))] for v in vals]
        owners = {smap.owner(sigs.key_from_set(r)) for r in rows}
        if owners == {"s0", "s1"}:
            break

    async def single_process_result():
        from dds_tpu.core.transport import InMemoryNet
        from dds_tpu.shard import build_constellation

        const = build_constellation(InMemoryNet(), shard_count=1,
                                    n_sentinent=0)
        server = DDSRestServer(const.router, ProxyConfig(port=0))
        await server.start()
        for row in rows:
            await _put(server.cfg.port, row)
        status, body = await http_request(
            "127.0.0.1", server.cfg.port, "GET",
            f"/SumAll?position=0&nsqr={pk.nsquare}", timeout=30.0)
        assert status == 200
        await server.stop()
        await const.stop()
        return json.loads(body)["result"]

    async def go():
        single = await single_process_result()
        fleet = await _MiniFleet().start()
        try:
            port = fleet.proxy_port()
            keys = [await _put(port, row) for row in rows]
            # the sample genuinely spans both groups
            owners = {
                fleet.deps["proxy"].server.abd.owner(k) for k in keys
            }
            assert owners == {"s0", "s1"}
            status, body = await http_request(
                "127.0.0.1", port, "GET",
                f"/SumAll?position=0&nsqr={pk.nsquare}", timeout=30.0)
            assert status == 200
            sharded = json.loads(body)["result"]
            assert sharded == single  # bit-for-bit across process shapes
            assert he.psse.decrypt(int(sharded)) == sum(vals)

            # kill the proxy process outright; a FRESH proxy bootstraps
            # the map from the groups' /shards and serves immediately
            await fleet.deps.pop("proxy").stop()
            await fleet.start_proxy("proxy2")
            port2 = fleet.proxy_port("proxy2")
            assert port2 != port
            for k, row in zip(keys, rows):
                status, body = await http_request(
                    "127.0.0.1", port2, "GET", f"/GetSet/{k}", timeout=10.0)
                assert status == 200
                assert json.loads(body)["contents"] == row
            status, _, body = await http_request_full(
                "127.0.0.1", port2, "GET", "/shards", timeout=5.0)
            assert status == 200
            assert ShardMap.from_wire(json.loads(body)["map"]).verify(SECRET)
        finally:
            await fleet.stop()

    run(go())


@pytest.mark.chaos
def test_cross_host_reshard_over_sockets_under_chaos():
    """Flagship loopback schedule: an S=2 fleet plus a standby group and
    a separate proxy, every hop on real TCP sockets, the proxy's and
    target group's fabrics wrapped in seeded ChaosNet schedules
    (delay + duplicate on the migration stream). A writer hammers a
    MOVING key over HTTP while POST /_reshard drives a live cross-host
    split. Asserts: the split activates epoch 2 everywhere, every acked
    write stays readable (the last one wins), the fence actually engaged
    (wrong-shard retries observed), and a Watchtower with per-group
    geometry reports zero quorum-intersection violations."""
    from dds_tpu.core.chaos import LinkFaults
    from dds_tpu.obs.metrics import metrics
    from dds_tpu.obs.watchtower import Watchtower
    from dds_tpu.utils.trace import tracer

    async def go():
        fleet = await _MiniFleet(standby=1).start()
        wt = Watchtower(quorum_size=3, n_replicas=4)
        wt.configure(group_geometry={"s0": (3, 4), "s1": (3, 4),
                                     "s2": (3, 4)})
        wt.attach(tracer)
        try:
            port = fleet.proxy_port()
            smap = initial_map(fleet.deps["proxy"].cfg)
            m2 = smap.split("s1", "s2").sign(SECRET)
            # seed rows until one key moves s1 -> s2 under the split
            rng = random.Random(5)
            moving = None
            while moving is None:
                row = [str(rng.randrange(1 << 16))]
                k = await _put(port, row)
                if smap.owner(k) == "s1" and m2.owner(k) == "s2":
                    moving = k
            def fence_count():
                total = 0
                for s in ("s0", "s1", "s2"):
                    total += (metrics.value(
                        "dds_wrong_shard_retries_total", shard=s) or 0)
                    for msg in ("Envelope", "Write", "ReadTagBatch"):
                        total += (metrics.value(
                            "dds_shard_fenced_total", shard=s, msg=msg)
                            or 0)
                return total

            fences_before = fence_count()
            # seeded chaos on the fabrics that carry the migration
            # stream: the proxy's sends (writes, manifests, chunks) and
            # the target group's internal traffic. The delays also
            # stretch the freeze->activate window so the hammering
            # writers demonstrably cross it.
            for name in ("proxy", "s2"):
                fleet.deps[name].net.default_faults = LinkFaults(
                    delay=0.005, jitter=0.02, duplicate=0.15
                )
            done = asyncio.Event()
            wrote = []

            async def writer(wid):
                i = 0
                while not (done.is_set() and i >= 3):
                    value = f"w{wid}-{i}"
                    status, _ = await http_request(
                        "127.0.0.1", port, "PUT",
                        f"/WriteElement/{moving}?position=0",
                        json.dumps({"value": value}).encode(),
                        timeout=20.0,
                    )
                    if status == 200:
                        wrote.append(value)
                    i += 1

            async def split():
                await asyncio.sleep(0.05)
                try:
                    status, body = await http_request(
                        "127.0.0.1", port, "POST", "/_reshard",
                        json.dumps(
                            {"source": "s1", "target": "s2"}
                        ).encode(),
                        timeout=45.0,
                    )
                    assert status == 200, body
                    return json.loads(body)
                finally:
                    done.set()

            _, _, split_result = await asyncio.gather(
                writer(0), writer(1), split()
            )
            assert split_result["epoch"] == 2
            assert wrote, "no write ever succeeded"
            # writes kept landing THROUGH the split, and the value served
            # afterwards is one of the final acked writes (two concurrent
            # writers: either one's last commit may hold the max tag —
            # but never a lost, misrouted, or phantom value)
            status, body = await http_request(
                "127.0.0.1", port, "GET", f"/GetSet/{moving}", timeout=10.0)
            assert status == 200
            final = json.loads(body)["contents"][0]
            last_idx = {
                wid: max(int(v.split("-")[1]) for v in wrote
                         if v.startswith(f"w{wid}-"))
                for wid in (0, 1)
                if any(v.startswith(f"w{wid}-") for v in wrote)
            }
            assert final in {
                f"w{wid}-{i}" for wid, i in last_idx.items()
            }, (final, last_idx)
            # the new owner serves it; the fleet agrees on epoch 2
            assert fleet.deps["proxy"].server.abd.owner(moving) == "s2"
            for gid, sp in fleet.s_ports.items():
                status, _, body = await http_request_full(
                    "127.0.0.1", sp, "GET", "/shards", timeout=5.0)
                assert status == 200
                assert json.loads(body)["map"]["epoch"] == 2, gid
            # the epoch fence engaged during the split (no silent
            # misroutes — stale routes were rejected and re-routed)
            assert fence_count() > fences_before
            bad = [v for v in wt.verdicts()
                   if v.invariant == "quorum_intersection"]
            assert not bad, bad
        finally:
            wt.detach()
            await fleet.stop()

    run(go())


# --------------------------------------------- trace context across TcpNet


def test_trace_context_propagates_across_tcp_sockets():
    """Satellite: one request through a loopback TCP proxy -> quorum hop
    still yields a SINGLE span tree — the `tc` frame field survives real
    socket serialization, not just the in-memory fabric."""
    from dds_tpu.run import launch
    from dds_tpu.utils.trace import tracer

    async def go():
        cfg = DDSConfig()
        cfg.transport.kind = "tcp"
        cfg.transport.port = 0
        cfg.proxy.port = 0
        cfg.recovery.enabled = False
        cfg.obs.audit_enabled = False
        dep = await launch(cfg)
        try:
            tracer.reset()
            status, _ = await http_request(
                "127.0.0.1", dep.server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": ["a", "b"]}).encode(), timeout=15.0)
            assert status == 200
            await asyncio.sleep(0.2)  # let straggler acks cross the sockets
        finally:
            await dep.stop()

        roots = tracer.events("http.POST.PutSet")
        assert len(roots) == 1
        root = roots[0]
        assert root.trace_id and root.parent_id is None
        tree = tracer.trace_events(root.trace_id)
        writes = [e for e in tree if e.name == "abd.write"]
        assert writes and all(e.parent_id == root.span_id for e in writes)
        # >=2f+1 DISTINCT replicas' handler spans joined THIS trace even
        # though every hop crossed a real TCP frame
        handlers = [e for e in tree if e.name == "replica.handle"]
        assert len({e.meta["replica"] for e in handlers}) >= 5
        assert all(e.trace_id == root.trace_id for e in handlers)

    run(go())


# ------------------------------------------------------- mint-node-keys


def test_mint_node_keys_provisions_files_and_stanza(tmp_path):
    pytest.importorskip(
        "cryptography", reason="nodeauth needs the cryptography package"
    )
    from dds_tpu.run import mint_node_keys
    from dds_tpu.utils import nodeauth

    hosts = ["10.0.0.1:2552", "10.0.0.2:2552", "10.0.0.3:2552"]
    stanza = mint_node_keys(3, str(tmp_path), hosts)
    # re-running reuses the SAME keys (never rotates under a live fleet)
    assert mint_node_keys(3, str(tmp_path), hosts) == stanza
    try:
        import tomllib
    except ModuleNotFoundError:
        import tomli as tomllib

    parsed = tomllib.loads(stanza)
    registry = parsed["security"]["node-public-keys"]
    assert sorted(registry) == sorted(hosts)
    for i, hp in enumerate(hosts):
        key = nodeauth.load_private((tmp_path / f"node_{i}.key").read_text())
        assert nodeauth.public_hex(key) == registry[hp]
        mode = (tmp_path / f"node_{i}.key").stat().st_mode & 0o777
        assert mode == 0o600


# ------------------------------------------------------------- load plane


def test_zipf_distribution_skew_and_percentile_math():
    from dds_tpu.clt.distribution import ZipfKeys
    from dds_tpu.fabric.loadgen import percentile

    keys = [f"K{i}" for i in range(50)]
    z = ZipfKeys(keys, s=1.2, rng=random.Random(1))
    counts = {}
    for _ in range(4000):
        k = z.pick()
        counts[k] = counts.get(k, 0) + 1
    # rank-1 dominates; the tail still gets traffic
    assert counts["K0"] == max(counts.values())
    assert counts["K0"] > 4000 / 50 * 4
    assert len(counts) > 25
    # weights sum to ~1 and are monotonically non-increasing
    w = [z.weight(r) for r in range(1, 51)]
    assert abs(sum(w) - 1.0) < 1e-9
    assert all(a >= b - 1e-12 for a, b in zip(w, w[1:]))
    with pytest.raises(ValueError):
        ZipfKeys([], 1.0)
    vals = sorted([0.01 * i for i in range(1, 101)])
    assert percentile(vals, 50) == pytest.approx(0.50)
    assert percentile(vals, 99) == pytest.approx(0.99)
    assert percentile([], 99) == 0.0


def test_open_loop_is_coordinated_omission_safe():
    """The property that separates this generator from the closed-loop
    client: a STALLED server does not slow the offered load, and the
    stall shows up in the percentiles because latency is measured from
    each request's scheduled arrival."""
    from dds_tpu.clt.distribution import ZipfKeys
    from dds_tpu.fabric.loadgen import OpenLoopLoad

    stall = 0.25

    async def handler(req):
        await asyncio.sleep(stall)
        return Response.json({"contents": ["1"]})

    async def go():
        server = HttpServer("127.0.0.1", 0, handler)
        await server.start()
        try:
            load = OpenLoopLoad(
                [f"127.0.0.1:{server.port}"], mix={"GetSet": 1.0},
                timeout=2.0, seed=4, max_outstanding=512,
            )
            # bypass seeding: the stub serves any key
            load.keys = ["K"]
            load._zipf = ZipfKeys(load.keys, 1.0, random.Random(0))
            rate, duration = 80.0, 1.0
            report = await load.run(rate, duration)
            # open loop: arrivals kept coming while every request sat in
            # the 250 ms stall (a closed loop would have collapsed to
            # ~4 requests per connection)
            assert report.scheduled > rate * duration * 0.6
            assert report.good > 20
            # CO-safety: no latency can undercut the server stall, and
            # the percentile floor proves scheduled-time measurement
            assert report.p50_ms >= stall * 1e3 * 0.95
            assert report.p99_ms >= report.p95_ms >= report.p50_ms
            # the SLO engine saw every sample (default 250ms objective:
            # the stall makes them all bad-latency)
            slo_routes = load.slo.report()["routes"]
            assert slo_routes["GetSet"]["windows"]["300s"]["total"] \
                >= report.completed
        finally:
            await server.stop()

    run(go())


def test_open_loop_against_constellation_reports_slo():
    """End-to-end smoke: the load plane drives a real (in-memory)
    constellation proxy and reports ordered percentiles, a per-class
    split, and the SLO engine's burn view."""
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.fabric.loadgen import OpenLoopLoad
    from dds_tpu.http.server import DDSRestServer, ProxyConfig
    from dds_tpu.shard import build_constellation

    async def go():
        const = build_constellation(InMemoryNet(), shard_count=2,
                                    n_sentinent=0)
        server = DDSRestServer(const.router, ProxyConfig(port=0))
        await server.start()
        try:
            load = OpenLoopLoad([f"127.0.0.1:{server.cfg.port}"], keys=10,
                                seed=9, timeout=3.0)
            keys = await load.seed()
            assert len(keys) == 10 and len(set(keys)) == 10
            reports = await load.sweep([60.0], 1.0)
            r = reports[0]
            assert r.scheduled > 30 and r.good > 30
            assert r.errors == 0 and r.failures == 0
            assert r.p50_ms <= r.p95_ms <= r.p99_ms
            assert set(r.per_class) <= {"interactive", "aggregate"}
            assert "interactive" in r.per_class
            assert "GetSet" in r.slo["routes"]
            d = r.to_dict()
            assert json.loads(json.dumps(d)) == d  # JSON-safe record
        finally:
            await server.stop()
            await const.stop()

    run(go())


def test_sentry_validates_multihost_load_records(tmp_path):
    from benchmarks.sentry import _check_multihost_records

    good = {
        "metric": "multihost load", "value": 98.0, "unit": "req/s",
        "vs_baseline": 1.0,
        "detail": {
            "rates": [40.0, 100.0], "processes": 3, "open_loop": True,
            "p50_ms": 8.0, "p95_ms": 20.0, "p99_ms": 70.0,
        },
    }
    bench = tmp_path / "benchmarks"
    bench.mkdir()
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_multihost_records(str(tmp_path)) == {"rows": 1}
    for mutate in (
        {"value": 0},                                   # no goodput
        {"detail": dict(good["detail"], processes=1)},  # not multi-process
        {"detail": dict(good["detail"], open_loop=False)},
        {"detail": dict(good["detail"], p50_ms=99.0)},  # p50 > p95
        {"detail": dict(good["detail"], rates=[])},
    ):
        (bench / "results.json").write_text(
            json.dumps([dict(good, **mutate)])
        )
        with pytest.raises(ValueError):
            _check_multihost_records(str(tmp_path))


# ------------------------------------------- flagship: real OS processes


@pytest.mark.slow
def test_flagship_multi_os_process_fleet(tmp_path):
    """The acceptance flagship on REAL OS processes: an S=2 constellation
    spread across 4 processes (two groups + a standby group + a separate
    proxy) on loopback TCP. Point ops and SumAll serve through the
    remote proxy; a live cross-host split (POST /_reshard) completes
    mid-load; killing and restarting the proxy process re-bootstraps the
    shard map from GET /shards without operator input."""
    from benchmarks.multihost_load import Fleet

    async def go():
        fleet = Fleet(str(tmp_path), standby=1)
        try:
            fleet.start()
            await fleet.wait_healthy(timeout=120.0)
            port = int(fleet.proxy_targets[0].rsplit(":", 1)[1])
            vals = [3, 141, 59, 26, 535, 8979]
            keys = [await _put(port, [str(v)], timeout=20.0) for v in vals]
            status, body = await http_request(
                "127.0.0.1", port, "GET", "/SumAll?position=0",
                timeout=30.0)
            assert status == 200
            assert json.loads(body)["result"] == str(sum(vals))

            async def writer():
                ok = 0
                for i in range(30):
                    status, _ = await http_request(
                        "127.0.0.1", port, "PUT",
                        f"/WriteElement/{keys[0]}?position=1",
                        json.dumps({"value": f"mid-{i}"}).encode(),
                        timeout=20.0,
                    )
                    ok += status == 200
                    await asyncio.sleep(0.02)
                return ok

            async def split():
                await asyncio.sleep(0.1)
                status, body = await http_request(
                    "127.0.0.1", port, "POST", "/_reshard",
                    json.dumps({"source": "s1"}).encode(), timeout=60.0)
                assert status == 200, body
                return json.loads(body)

            ok_writes, split_result = await asyncio.gather(writer(), split())
            assert split_result["epoch"] == 2
            assert "s2" in split_result["groups"]
            assert ok_writes > 0
            # the fleet still serves every key and the SAME aggregate
            status, body = await http_request(
                "127.0.0.1", port, "GET", "/SumAll?position=0",
                timeout=30.0)
            assert status == 200
            assert json.loads(body)["result"] == str(sum(vals))

            # kill the proxy PROCESS; a restarted one re-bootstraps the
            # epoch-2 map from the group processes' GET /shards
            proxy = fleet.procs.pop("proxy0")
            proxy.terminate()
            proxy.wait(timeout=15)
            fleet.spawn("proxy0")
            await fleet.wait_healthy(timeout=120.0)
            status, _, body = await http_request_full(
                "127.0.0.1", port, "GET", "/shards", timeout=10.0)
            assert status == 200
            d = json.loads(body)
            assert d["map"]["epoch"] == 2 and "s2" in d["map"]["groups"]
            for k, v in zip(keys, vals):
                status, body = await http_request(
                    "127.0.0.1", port, "GET", f"/GetSet/{k}", timeout=20.0)
                assert status == 200
                assert json.loads(body)["contents"][0] == str(v)
        finally:
            fleet.stop()

    asyncio.run(go())
