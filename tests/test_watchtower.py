"""Watchtower auditing, SLO engine, and perf-sentry tests.

Unit layer: synthetic traces fed through a private Tracer must produce
exactly the expected verdicts (dropped-ack quorums, stale tags, illegal
breaker transitions, non-converging repairs) and NO verdicts on clean
shapes. End-to-end layer: a seeded ChaosNet cluster with a Trudy-style
forging coordinator MUST yield the tag_monotonicity + quorum_intersection
verdicts with the offending trace_id and a flight incident, while the
identical schedule without the attack audits clean. Plus: SLO burn math
on a fake clock, the `GET /slo` route, sentry baseline round-trip and the
CLI's non-zero exit on a synthetically-inflated kernel timing.
"""

import asyncio
import json
import os
import random
import subprocess
import sys
import time

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.obs import sentry
from dds_tpu.obs.flight import flight
from dds_tpu.obs.slo import RouteSlo, SloEngine
from dds_tpu.obs.watchtower import Watchtower
from dds_tpu.utils import sigs
from dds_tpu.utils.trace import Tracer, tracer

pytestmark = pytest.mark.audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(coro):
    return asyncio.run(coro)


def make_wt(**kw):
    kw.setdefault("quorum_size", 5)
    kw.setdefault("n_replicas", 7)
    wt = Watchtower(**kw)
    t = Tracer()
    wt.attach(t)
    return wt, t


def commit_op(t, name, key, seq, tid, read_replicas=(), write_replicas=(),
              coordinator="replica-0", op=None):
    """Synthesize one committed quorum op trace: root -> abd span (ok,
    tagged) -> replica.handle children per phase."""
    with t.span(f"http.{name}"):
        with t.span(
            "abd.write" if name == "write" else "abd.fetch",
            coordinator=coordinator, ok=True,
            op=op or ("write" if name == "write" else "read"),
            key=key, seq=seq, tag_id=tid,
        ):
            for r in read_replicas:
                with t.span("replica.handle", replica=r,
                            msg="ReadTag" if name == "write" else "Read",
                            key=key):
                    pass
            for r in write_replicas:
                with t.span("replica.handle", replica=r, msg="Write", key=key):
                    pass


R7 = [f"replica-{i}" for i in range(7)]


# ------------------------------------------------------------ unit: quorum


def test_clean_write_trace_audits_without_verdicts():
    wt, t = make_wt()
    commit_op(t, "write", "k1", 1, "replica-0",
              read_replicas=R7[:5], write_replicas=R7[1:6])
    assert wt.verdicts() == []
    assert wt.stats()["traces_audited"] == 1
    assert wt.stats()["ops_audited"] == 1


def test_dropped_ack_quorum_is_flagged():
    wt, t = make_wt()
    # coordinator answered after only 2 Write handlers: a forged quorum
    commit_op(t, "write", "k1", 1, "replica-0",
              read_replicas=R7[:5], write_replicas=R7[:2])
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["quorum_intersection"]
    assert any("write_phase=2<5" in p for p in vs[0].detail["problems"])


def test_quorum_intersection_bound_is_checked():
    wt, t = make_wt()
    # both phases reach quorum size but share only 2 < 2q-n = 3 replicas
    # (physically impossible with n=7 honest replicas — exactly what the
    # auditor exists to notice)
    extra = [f"replica-{i}" for i in range(7, 10)]
    commit_op(t, "write", "k1", 1, "replica-0",
              read_replicas=R7[:5], write_replicas=R7[3:5] + extra)
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["quorum_intersection"]
    assert any("intersection=2<3" in p for p in vs[0].detail["problems"])


def test_read_fast_path_skips_write_phase_legally():
    wt, t = make_wt()
    commit_op(t, "read", "k1", 1, "replica-0", read_replicas=R7[:5])
    assert wt.verdicts() == []


# ------------------------------------------------------- unit: tag ordering


def test_tag_monotonicity_across_traces():
    wt, t = make_wt(check_quorum=False)
    commit_op(t, "write", "k", 2, "replica-1")
    time.sleep(0.005)  # strict real-time order between the two commits
    commit_op(t, "read", "k", 1, "replica-0")
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["tag_monotonicity"]
    assert vs[0].detail["tag"] == [1, "replica-0"]
    assert vs[0].detail["prior_tag"] == [2, "replica-1"]
    assert vs[0].trace_id is not None


def test_duplicate_tag_mint_is_flagged():
    wt, t = make_wt(check_quorum=False)
    commit_op(t, "write", "k", 3, "replica-1")
    time.sleep(0.005)
    commit_op(t, "write", "k", 3, "replica-1")
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["tag_monotonicity"]
    assert vs[0].detail["violation_kind"] == "duplicate_mint"


def test_forward_tags_and_other_keys_stay_clean():
    wt, t = make_wt(check_quorum=False)
    commit_op(t, "write", "k", 1, "replica-0")
    time.sleep(0.002)
    commit_op(t, "write", "k", 2, "replica-1")
    time.sleep(0.002)
    commit_op(t, "read", "k", 2, "replica-1")
    commit_op(t, "write", "other", 1, "replica-0")
    assert wt.verdicts() == []


def test_read_sees_latest_within_one_trace():
    wt, t = make_wt(check_quorum=False)
    with t.span("http.GET.agg"):
        with t.span("abd.write", coordinator="replica-0", ok=True, op="write",
                    key="k", seq=5, tag_id="replica-0"):
            pass
        time.sleep(0.005)
        with t.span("abd.fetch", coordinator="replica-1", ok=True, op="read",
                    key="k", seq=4, tag_id="replica-1"):
            pass
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["read_sees_latest"]
    assert vs[0].detail["read_tag"] == [4, "replica-1"]


# ------------------------------------------------- unit: state machines


def test_breaker_half_open_requires_open():
    wt, t = make_wt()
    t.event("breaker.open", target="replica-1")
    t.event("breaker.half_open", target="replica-1")
    t.event("breaker.closed", target="replica-1")
    assert wt.verdicts() == []
    t.event("breaker.half_open", target="replica-2")  # closed -> half_open
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["breaker_legality"]
    assert vs[0].detail["transition"] == "closed->half_open"


def test_suspicion_excluded_coordinator_must_not_commit():
    wt, t = make_wt(check_quorum=False)
    for _ in range(3):
        t.event("abd.coordinator_violation", node="replica-3")
    time.sleep(0.005)
    commit_op(t, "read", "k", 1, "replica-0", coordinator="replica-3")
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["suspicion_legality"]
    assert vs[0].detail["coordinator"] == "replica-3"


def test_repair_convergence_checks_installed_vs_advertised():
    wt, t = make_wt()
    with t.span("antientropy.sync", replica="replica-0"):
        t.event("audit.repair", replica="replica-0", peer="replica-1",
                key="good", src_seq=4, src_id="a", seq=4, tag_id="a")
        t.event("audit.repair", replica="replica-0", peer="replica-1",
                key="bad", src_seq=9, src_id="z", seq=3, tag_id="a")
    vs = wt.verdicts()
    assert [v.invariant for v in vs] == ["repair_convergence"]
    assert vs[0].detail["key"] == "bad"
    assert vs[0].detail["advertised"] == [9, "z"]
    assert vs[0].detail["installed"] == [3, "a"]


# --------------------------------------------------- e2e: clusters + attacks


class StaleForgerNode(BFTABDNode):
    """Trudy-style coordinator: holds the real proxy MAC secret and
    answers reads with a properly-signed FORGED stale (tag, value) —
    undetectable to the client's cryptographic checks, detectable only by
    auditing the committed tag sequence."""

    forged_tag = (1, "forged")
    forged_value = ["stale"]
    forging = True

    async def _healthy(self, sender, msg):
        match msg:
            case M.Envelope(M.IRead(key), nonce, _sig) if self.forging:
                tag = M.ABDTag(*self.forged_tag)
                challenge = nonce + self.cfg.nonce_increment
                sig = sigs.proxy_signature(
                    self.cfg.proxy_mac_secret, key, challenge,
                    [self.forged_value, sigs.tag_payload(tag)],
                )
                self._send(sender, M.Envelope(
                    M.IReadReply(key, self.forged_value, tag=tag),
                    challenge, sig,
                ))
            case _:
                await super()._healthy(sender, msg)


class CheatingCoordinator(BFTABDNode):
    """Answers a write instantly with a valid proxy MAC — no quorum ever
    ran. The client cannot tell; the trace can."""

    async def _healthy(self, sender, msg):
        match msg:
            case M.Envelope(M.IWrite(key, _v), nonce, _sig):
                self._seq_floor += 1
                tag = M.ABDTag(self._seq_floor, self.name)
                challenge = nonce + self.cfg.nonce_increment
                sig = sigs.proxy_signature(
                    self.cfg.proxy_mac_secret, key, challenge,
                    sigs.tag_payload(tag),
                )
                self._send(sender, M.Envelope(
                    M.IWriteReply(key, tag=tag), challenge, sig,
                ))
            case _:
                await super()._healthy(sender, msg)


def _chaos_cluster(seed, special_cls=None, special_addr="replica-6"):
    net = ChaosNet(InMemoryNet(), seed=seed)
    net.default_faults = LinkFaults(delay=0.001, jitter=0.002)
    replicas = {}
    for a in R7:
        cls = special_cls if (special_cls and a == special_addr) else BFTABDNode
        replicas[a] = cls(a, R7, "supervisor", net,
                          ReplicaConfig(quorum_size=5))
    client = AbdClient(
        "proxy-0", net, R7,
        AbdClientConfig(request_timeout=2.0, quorum_size=5),
    )
    client.replicas._rng = random.Random(5)
    return net, client, replicas


async def _forged_tag_schedule(seed, attack: bool):
    """Two honest writes, then a read steered through replica-6. With
    `attack` the read is served a forged stale tag; without, replica-6
    answers honestly — the identical schedule minus the forgery."""
    net, client, replicas = _chaos_cluster(
        seed, special_cls=StaleForgerNode
    )
    replicas["replica-6"].forging = attack
    others = tuple(a for a in R7 if a != "replica-6")
    try:
        await client.write_set("KEY", ["v1"], )
        await client.write_set("KEY", ["v2"], )
        await asyncio.sleep(0.01)  # strict real-time order before the read
        value, tag, coord = await client.fetch_set_attributed(
            "KEY", exclude=others
        )
        assert coord == "replica-6"
        if attack:
            assert value == ["stale"] and tag.seq == 1  # the forgery landed
        else:
            assert value == ["v2"]
        await net.quiesce()
    finally:
        await net.stop()


def test_forged_tag_under_chaos_yields_exact_verdicts(tmp_path):
    """Acceptance: seeded ChaosNet + forging coordinator -> the auditor
    reports tag_monotonicity (stale committed tag) AND quorum_intersection
    (no read quorum ever served the forged reply), both carrying the
    offending read's trace_id, and files flight incidents with the trace."""
    wt = Watchtower(quorum_size=5, n_replicas=7)
    wt.attach(tracer)
    flight.configure(dir=str(tmp_path), min_interval=0.0)
    try:
        run(_forged_tag_schedule(seed=21, attack=True))
    finally:
        flight.configure(dir="")
        wt.detach()
    vs = wt.verdicts()
    by_inv = {v.invariant: v for v in vs}
    assert set(by_inv) == {"tag_monotonicity", "quorum_intersection"}
    mono = by_inv["tag_monotonicity"]
    assert mono.detail["key"] == "KEY"
    assert mono.detail["tag"] == [1, "forged"]
    assert mono.detail["coordinator"] == "replica-6"
    # both verdicts blame the SAME offending trace: the forged read
    assert mono.trace_id is not None
    assert by_inv["quorum_intersection"].trace_id == mono.trace_id

    incidents = sorted(tmp_path.glob("incident-*audit_tag_monotonicity*.jsonl"))
    assert incidents
    lines = [json.loads(l) for l in open(incidents[0])]
    header = lines[0]
    assert header["trace_id"] == mono.trace_id
    trace_lines = [l for l in lines[1:] if l.get("section") == "trace"]
    assert any(l["name"] == "abd.fetch" for l in trace_lines)
    # the index names the incident without globbing
    idx = [json.loads(l) for l in open(tmp_path / "index.jsonl")]
    assert any(e["kind"] == "audit_tag_monotonicity"
               and e["trace_id"] == mono.trace_id for e in idx)


def test_identical_schedule_without_attack_is_clean():
    wt = Watchtower(quorum_size=5, n_replicas=7)
    wt.attach(tracer)
    try:
        run(_forged_tag_schedule(seed=21, attack=False))
    finally:
        wt.detach()
    assert wt.verdicts() == []
    assert wt.stats()["traces_audited"] >= 3  # both writes + the read


def test_dropped_ack_quorum_e2e():
    """A committed write whose coordinator never ran a quorum -> exactly
    one quorum_intersection verdict."""
    wt = Watchtower(quorum_size=5, n_replicas=7)
    wt.attach(tracer)
    try:
        async def go():
            net, client, _ = _chaos_cluster(9, special_cls=CheatingCoordinator)
            # force the cheater to coordinate: strike every other replica
            # out of the trusted set for this client
            for a in R7:
                if a != "replica-6":
                    for _ in range(3):
                        client.replicas.increment_suspicion(a)
            try:
                await client.write_set("Q", ["v"])
                await net.quiesce()
            finally:
                await net.stop()

        run(go())
    finally:
        wt.detach()
    vs = [v for v in wt.verdicts() if v.invariant == "quorum_intersection"]
    assert len(vs) == 1
    assert vs[0].detail["key"] == "Q"
    assert vs[0].detail["read_phase"] == [] and vs[0].detail["write_phase"] == []


def test_clean_chaos_run_zero_violations_property():
    """Property: a clean seeded-chaos run (no attack) audits every trace
    and yields ZERO violations."""
    wt = Watchtower(quorum_size=5, n_replicas=7)
    wt.attach(tracer)
    try:
        async def go():
            net, client, _ = _chaos_cluster(33)
            rng = random.Random(4)
            try:
                keys = [f"pk-{i}" for i in range(4)]
                for i in range(12):
                    k = rng.choice(keys)
                    if rng.random() < 0.5:
                        await client.write_set(k, [f"v{i}"])
                    else:
                        await client.fetch_set(k)
                await net.quiesce()
            finally:
                await net.stop()

        run(go())
    finally:
        wt.detach()
    assert wt.verdicts() == []
    st = wt.stats()
    assert st["traces_audited"] >= 12 and st["ops_audited"] >= 12


def test_launch_attaches_and_stop_detaches_watchtower():
    """launch() wires the global auditor to the deployment's quorum
    geometry; stop() detaches it so a later deployment (or test cluster)
    is never audited against stale q/n."""
    from dds_tpu.obs.watchtower import watchtower as global_wt
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    async def go():
        cfg = DDSConfig()
        cfg.proxy.port = 0
        cfg.recovery.enabled = False
        cfg.recovery.anti_entropy_enabled = False
        dep = await launch(cfg)
        try:
            assert global_wt.attached
            assert global_wt.quorum_size == 5
            assert global_wt.n_replicas == 7  # 9 endpoints - 2 sentinent
            assert global_wt.check_quorum
        finally:
            await dep.stop()
        assert not global_wt.attached

    run(go())


# ------------------------------------------------------------------ SLO


def test_slo_burn_math_and_windows():
    clk = [0.0]
    eng = SloEngine(default=RouteSlo(objective=0.9, latency_ms=100.0),
                    windows=(60.0, 600.0), burn_alert=2.0,
                    clock=lambda: clk[0])
    for _ in range(8):
        eng.observe("GetSet", 200, 0.010)
    eng.observe("GetSet", 200, 0.500)   # too slow: burns budget
    eng.observe("GetSet", 503, 0.010)   # server error: burns budget
    eng.observe("GetSet", 404, 0.010)   # client error, fast: GOOD
    r = eng.report()["routes"]["GetSet"]
    w = r["windows"]["60s"]
    assert w["total"] == 11 and w["bad"] == 2
    assert w["bad_latency"] == 1 and w["bad_error"] == 1
    # bad fraction 2/11 over budget 0.1 -> burn ~1.82 < alert 2.0
    assert abs(w["burn_rate"] - (2 / 11) / 0.1) < 1e-3
    assert r["alert"] is False

    # a cliff: 10 straight errors pushes burn over the alert line in BOTH
    # windows
    for _ in range(10):
        eng.observe("GetSet", 503, 0.010)
    r = eng.report()["routes"]["GetSet"]
    assert r["alert"] is True
    assert r["windows"]["60s"]["burn_rate"] >= 2.0

    # the fast window forgets, the slow one remembers
    clk[0] = 120.0
    r = eng.report()["routes"]["GetSet"]
    assert r["windows"]["60s"]["total"] == 0
    assert r["windows"]["600s"]["total"] == 21
    assert r["alert"] is False  # fast window no longer corroborates


def test_slo_per_route_overrides_and_gauges():
    clk = [0.0]
    eng = SloEngine(
        default=RouteSlo(0.99, 100.0),
        routes={"SumAll": RouteSlo(0.95, 1000.0)},
        windows=(60.0, 600.0), clock=lambda: clk[0],
    )
    eng.observe("SumAll", 200, 0.5)  # slow for default, fine for SumAll
    r = eng.report()["routes"]["SumAll"]
    assert r["objective"] == 0.95
    assert r["windows"]["60s"]["bad"] == 0

    from dds_tpu.obs.metrics import Registry
    reg = Registry()
    eng.export_gauges(reg)
    assert reg.value("dds_slo_objective", route="SumAll") == 0.95
    assert reg.value("dds_slo_burn_rate", route="SumAll", window="60s") == 0.0
    assert reg.value("dds_slo_error_budget_remaining", route="SumAll") == 1.0
    text = reg.render()
    assert "# TYPE dds_slo_burn_rate gauge" in text
    assert "# HELP dds_slo_burn_rate" in text


async def _rest_stack(**proxy_kw):
    net = ChaosNet(InMemoryNet(), seed=11)
    net.default_faults = LinkFaults(delay=0.001, jitter=0.002)
    replicas = {
        a: BFTABDNode(a, R7, "supervisor", net, ReplicaConfig(quorum_size=5))
        for a in R7
    }
    abd = AbdClient("proxy-0", net, R7,
                    AbdClientConfig(request_timeout=2.0, quorum_size=5))
    server = DDSRestServer(
        abd,
        ProxyConfig(host="127.0.0.1", port=0, request_budget=10.0, **proxy_kw),
    )
    await server.start()
    return net, server


def test_slo_route_serves_parseable_burn_state():
    """Acceptance: GET /slo returns parseable per-route objective/burn
    state (and the audit summary riding along)."""

    async def go():
        net, server = await _rest_stack()
        try:
            status, _ = await http_request(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": ["a"]}).encode(), timeout=10.0,
            )
            assert status == 200
            status, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/slo", timeout=10.0,
            )
            assert status == 200
            await net.quiesce()
            return json.loads(body)
        finally:
            await server.stop()

    out = run(go())
    routes = out["slo"]["routes"]
    assert "PutSet" in routes
    put = routes["PutSet"]
    assert 0 < put["objective"] <= 1
    for wname in put["windows"]:
        assert set(put["windows"][wname]) >= {
            "total", "bad", "burn_rate", "bad_fraction",
        }
    assert put["windows"][f"{int(out['slo']['windows_s'][0])}s"]["total"] >= 1
    assert "budget_remaining" in put and "alert" in put
    assert "violations" in out["audit"]


# ---------------------------------------------------------------- sentry


def _fake_kernel_trace():
    t = Tracer()
    for d in (1.0, 1.1, 1.2, 1.3, 1.4):
        t.record("kernel.foldmany.dispatch", d, R=2, P2=2)
        t.record("kernel.foldmany.execute", d * 2, R=2, P2=2)
    return t


# pin the baseline namespace so these tests (and their CLI subprocesses,
# which inherit the env) agree on keys regardless of the host's backend
@pytest.fixture(autouse=True)
def _pin_sentry_platform(monkeypatch):
    monkeypatch.setenv("DDS_SENTRY_PLATFORM", "cpu")


def test_sentry_collect_keys_by_platform_name_and_shape():
    stats = sentry.collect(_fake_kernel_trace())
    assert list(stats) == ["cpu::foldmany[R=2,P2=2]"]
    d = stats["cpu::foldmany[R=2,P2=2]"]["dispatch"]
    assert d["count"] == 5 and d["p50_ms"] == 1.2 and d["p95_ms"] == 1.4


def test_sentry_platform_namespacing_never_crosses_environments():
    """Satellite-f: a CPU-fabric run's rows must not gate (or ratchet)
    against an on-chip baseline's rows — the platform prefix keeps the
    key sets disjoint, so compare() has an empty intersection."""
    cpu_stats = sentry.collect(_fake_kernel_trace())
    os.environ["DDS_SENTRY_PLATFORM"] = "tpu"
    try:
        tpu_stats = sentry.collect(_fake_kernel_trace())
    finally:
        os.environ["DDS_SENTRY_PLATFORM"] = "cpu"
    assert set(cpu_stats).isdisjoint(tpu_stats)
    # a 10x-slower CPU run vs a TPU baseline: no findings, nothing shared
    slow_cpu = {k: {ph: {**s, "p50_ms": s["p50_ms"] * 10}
                    for ph, s in e.items()} for k, e in cpu_stats.items()}
    assert sentry.compare(tpu_stats, slow_cpu) == []
    # and a merge into one shared file keeps both environments' rows
    merged = dict(tpu_stats)
    merged.update(slow_cpu)
    assert sentry.compare(merged, slow_cpu) == []  # only cpu rows compare


def test_sentry_baseline_roundtrip_and_merge(tmp_path):
    p = str(tmp_path / "base.json")
    stats = sentry.collect(_fake_kernel_trace())
    sentry.save_baseline(stats, p)
    assert sentry.load_baseline(p) == stats
    # merge keeps the committed baseline unless overwrite
    slower = {k: {ph: {**s, "p50_ms": s["p50_ms"] * 10}
                  for ph, s in e.items()} for k, e in stats.items()}
    sentry.save_baseline(slower, p)
    assert sentry.load_baseline(p) == stats
    sentry.save_baseline(slower, p, overwrite=True)
    assert sentry.load_baseline(p) == slower
    # malformed file -> typed error, not garbage comparisons
    (tmp_path / "bad.json").write_text('{"kernels": {"k": {"dispatch": "x"}}}')
    with pytest.raises(ValueError):
        sentry.load_baseline(str(tmp_path / "bad.json"))


def test_sentry_compare_flags_inflated_timings():
    base = sentry.collect(_fake_kernel_trace())
    fresh = {k: {ph: dict(s) for ph, s in e.items()} for k, e in base.items()}
    assert sentry.compare(base, fresh) == []
    fresh["cpu::foldmany[R=2,P2=2]"]["execute"]["p50_ms"] *= 3  # 3x regression
    findings = sentry.compare(base, fresh, threshold=0.20)
    assert len(findings) == 1
    f = findings[0]
    assert (f["phase"], f["stat"]) == ("execute", "p50_ms")
    assert f["ratio"] >= 3.0
    # sub-floor jitter on a tiny kernel is not a regression
    tiny_b = {"k": {"dispatch": {"p50_ms": 0.01, "p95_ms": 0.01, "count": 5}}}
    tiny_f = {"k": {"dispatch": {"p50_ms": 0.03, "p95_ms": 0.03, "count": 5}}}
    assert sentry.compare(tiny_b, tiny_f) == []


def _run_sentry_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "sentry.py"), *args],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )


def test_sentry_cli_gates_on_regression(tmp_path):
    """Acceptance: the sentry CLI exits non-zero when a fresh run's kernel
    timing is synthetically inflated past the stored baseline."""
    stats = sentry.collect(_fake_kernel_trace())
    base_path = str(tmp_path / "baseline.json")
    sentry.save_baseline(stats, base_path)
    inflated = {k: {ph: {**s, "p50_ms": s["p50_ms"] * 2, "p95_ms": s["p95_ms"] * 2}
                    for ph, s in e.items()} for k, e in stats.items()}
    fresh_path = tmp_path / "fresh.json"
    fresh_path.write_text(json.dumps(inflated))

    p = _run_sentry_cli("--baseline", base_path, "--fresh", str(fresh_path))
    assert p.returncode == 1, p.stdout + p.stderr
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["ok"] is False and row["regressions"]
    assert row["regressions"][0]["kernel"] == "cpu::foldmany[R=2,P2=2]"

    # identical stats pass the gate
    same = tmp_path / "same.json"
    same.write_text(json.dumps(stats))
    p = _run_sentry_cli("--baseline", base_path, "--fresh", str(same))
    assert p.returncode == 0, p.stdout + p.stderr


def test_sentry_cli_check_smoke(tmp_path):
    """The CPU-only CI smoke: --check parses the baseline (or reports a
    clean absence) with exit 0, and exits 2 on a corrupted file."""
    stats = sentry.collect(_fake_kernel_trace())
    base_path = str(tmp_path / "baseline.json")
    sentry.save_baseline(stats, base_path)
    p = _run_sentry_cli("--check", "--baseline", base_path)
    assert p.returncode == 0, p.stdout + p.stderr
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["ok"] is True and row["kernels"] == 1

    p = _run_sentry_cli("--check", "--baseline", str(tmp_path / "missing.json"))
    assert p.returncode == 0

    bad = tmp_path / "corrupt.json"
    bad.write_text("{not json")
    p = _run_sentry_cli("--check", "--baseline", str(bad))
    assert p.returncode == 2


def test_emit_persists_kernel_baseline(tmp_path, monkeypatch):
    from benchmarks import common

    path = tmp_path / "kb.json"
    monkeypatch.setenv("DDS_KERNEL_BASELINE", str(path))
    tracer.record("kernel.emit_probe.dispatch", 2.0, k=4)
    tracer.record("kernel.emit_probe.execute", 3.0, k=4)
    common.emit("m", 1.0, "ops/s", 1.0)
    kernels = sentry.load_baseline(str(path))
    assert "cpu::emit_probe[k=4]" in kernels
    assert kernels["cpu::emit_probe[k=4]"]["execute"]["p50_ms"] == 3.0


# ------------------------------------------------------- metrics satellite


def test_metrics_help_backfill_and_escaping():
    from dds_tpu.obs.metrics import Registry

    r = Registry()
    r.set("g_state", 1)                       # first touch: no help
    r.set("g_state", 2, help="state\nwith \\ tricky text")
    text = r.render()
    assert "# HELP g_state state\\nwith \\\\ tricky text" in text
    assert "# TYPE g_state gauge" in text
    # backfill never downgrades an existing help
    r.inc("c_total", help="first")
    r.inc("c_total", help="second")
    assert "# HELP c_total first" in r.render()


# --------------------------------------------------- flight index satellite


def test_flight_index_lines_and_prune_rewrite(tmp_path):
    from dds_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(dir=str(tmp_path), max_incidents=2, min_interval=0.0)
    for i in range(4):
        assert fr.record(f"kind_{i}", trace_id=f"t{i}") is not None
    files = {p.name for p in tmp_path.glob("incident-*.jsonl")}
    assert len(files) == 2
    idx = [json.loads(l) for l in open(tmp_path / "index.jsonl")]
    # pruned incidents were dropped from the index; survivors match files
    assert {e["path"] for e in idx} == files
    assert all({"ts", "kind", "trace_id", "path"} <= set(e) for e in idx)
    assert [e["kind"] for e in idx] == ["kind_2", "kind_3"]


# ----------------------------------------------------- bench.py satellite


def test_bench_probe_failure_classification():
    import bench

    d = bench._classify_failure(None, "", "WARNING: platform experimental\n")
    assert d["kind"] == "hang_timeout" and d["rc"] is None

    d = bench._classify_failure(
        1, "", "RuntimeError: UNAVAILABLE: TPU backend setup error\n"
    )
    assert d["kind"] == "unavailable"
    assert any("UNAVAILABLE" in l for l in d["tail"])

    err = "WARNING: noise\nTraceback (most recent call last):\nValueError: boom\n"
    d = bench._classify_failure(2, "", err)
    assert d["kind"] == "crash"
    # error-ish lines beat the warning noise that used to clip the detail
    assert any("ValueError" in l for l in d["tail"])
    assert not any(l.startswith("WARNING") for l in d["tail"])
