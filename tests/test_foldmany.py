"""Segmented multi-request folds (ops/foldmany): one dispatch, R results."""

import random

import pytest

from dds_tpu.ops import foldmany

rng = random.Random(17)


def _want(f, n):
    acc = 1
    for c in f:
        acc = acc * c % n
    return acc


@pytest.mark.parametrize("kernel", ["jnp", "v2"])
def test_fold_many_ragged_matches_int(kernel):
    n = rng.getrandbits(512) | (1 << 511) | 1
    folds = [
        [rng.randrange(1, n) for _ in range(k)] for k in (1, 3, 8, 13, 40)
    ]
    got = foldmany.fold_many(folds, n, kernel=kernel)
    assert got == [_want(f, n) for f in folds]


def test_fold_many_single_request_and_request_padding():
    n = rng.getrandbits(256) | (1 << 255) | 1
    # R=3 pads the request axis to 4 with dummy folds; results must be exact
    folds = [[rng.randrange(1, n) for _ in range(5)] for _ in range(3)]
    assert foldmany.fold_many(folds, n) == [_want(f, n) for f in folds]
    # R=1 degenerates to a plain fold
    one = [[rng.randrange(1, n) for _ in range(9)]]
    assert foldmany.fold_many(one, n) == [_want(one[0], n)]


def test_backend_fold_many_dispatches_kernel_family():
    from dds_tpu.models.backend import TpuBackend

    n = rng.getrandbits(256) | (1 << 255) | 1
    folds = [[rng.randrange(1, n) for _ in range(4)] for _ in range(2)]
    be = TpuBackend(pallas=True, kernel="v2", min_device_batch=0)
    assert be.modmul_fold_many(folds, n) == [_want(f, n) for f in folds]


def test_fold_many_cache_keys_on_karatsuba_mode_and_interpret(monkeypatch):
    """Flipping DDS_KARATSUBA mid-process must MISS the compiled-fn cache
    (a stale hit would silently serve the other variant's kernel)."""
    from dds_tpu.ops.montgomery import ModCtx

    n = rng.getrandbits(256) | (1 << 255) | 1
    ctx = ModCtx.make(n)
    monkeypatch.delenv("DDS_KARATSUBA", raising=False)
    foldmany._fold_many_fn(ctx, "v2", 2)
    keys_off = {k for k in foldmany._FN_CACHE if k[0] == ctx.n}
    monkeypatch.setenv("DDS_KARATSUBA", "2")
    foldmany._fold_many_fn(ctx, "v2", 2)
    keys_fused = {k for k in foldmany._FN_CACHE if k[0] == ctx.n}
    assert keys_fused != keys_off  # a NEW entry was compiled, not reused
    assert any(k[-1] == "fused" for k in keys_fused - keys_off)


def test_prod_tb_env_flag_validated_loudly(monkeypatch):
    """DDS_PROD_TB typos fail at flag-read with an actionable message, not
    deep inside a trace (ops/flags.prod_tb; used by mont_mxu._tb_for)."""
    from dds_tpu.ops.flags import prod_tb

    monkeypatch.delenv("DDS_PROD_TB", raising=False)
    assert prod_tb() is None
    monkeypatch.setenv("DDS_PROD_TB", "512")
    assert prod_tb() == 512
    for bad in ("12eight", "-128", "0", "100"):
        monkeypatch.setenv("DDS_PROD_TB", bad)
        with pytest.raises(ValueError, match="DDS_PROD_TB"):
            prod_tb()


def test_fold_many_fuzz_against_int():
    """Randomized shapes: R in 1..6 requests, widths 1..70, two moduli
    sizes, both kernels — every segment's product must match python ints
    (guards the elem-major layout + per-request R-power accounting)."""
    for trial in range(6):
        bits = 256 if trial % 2 else 384
        n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
        folds = [
            [rng.randrange(1, n) for _ in range(rng.randint(1, 70))]
            for _ in range(rng.randint(1, 6))
        ]
        kernel = "v2" if trial % 3 == 0 else "jnp"
        got = foldmany.fold_many(folds, n, kernel=kernel)
        assert got == [_want(f, n) for f in folds], (trial, kernel)
