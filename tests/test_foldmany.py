"""Segmented multi-request folds (ops/foldmany): one dispatch, R results."""

import random

import pytest

from dds_tpu.ops import foldmany

rng = random.Random(17)


def _want(f, n):
    acc = 1
    for c in f:
        acc = acc * c % n
    return acc


@pytest.mark.parametrize("kernel", ["jnp", "v2"])
def test_fold_many_ragged_matches_int(kernel):
    n = rng.getrandbits(512) | (1 << 511) | 1
    folds = [
        [rng.randrange(1, n) for _ in range(k)] for k in (1, 3, 8, 13, 40)
    ]
    got = foldmany.fold_many(folds, n, kernel=kernel)
    assert got == [_want(f, n) for f in folds]


def test_fold_many_single_request_and_request_padding():
    n = rng.getrandbits(256) | (1 << 255) | 1
    # R=3 pads the request axis to 4 with dummy folds; results must be exact
    folds = [[rng.randrange(1, n) for _ in range(5)] for _ in range(3)]
    assert foldmany.fold_many(folds, n) == [_want(f, n) for f in folds]
    # R=1 degenerates to a plain fold
    one = [[rng.randrange(1, n) for _ in range(9)]]
    assert foldmany.fold_many(one, n) == [_want(one[0], n)]


def test_backend_fold_many_dispatches_kernel_family():
    from dds_tpu.models.backend import TpuBackend

    n = rng.getrandbits(256) | (1 << 255) | 1
    folds = [[rng.randrange(1, n) for _ in range(4)] for _ in range(2)]
    be = TpuBackend(pallas=True, kernel="v2", min_device_batch=0)
    assert be.modmul_fold_many(folds, n) == [_want(f, n) for f in folds]


def test_fold_many_fuzz_against_int():
    """Randomized shapes: R in 1..6 requests, widths 1..70, two moduli
    sizes, both kernels — every segment's product must match python ints
    (guards the elem-major layout + per-request R-power accounting)."""
    for trial in range(6):
        bits = 256 if trial % 2 else 384
        n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
        folds = [
            [rng.randrange(1, n) for _ in range(rng.randint(1, 70))]
            for _ in range(rng.randint(1, 6))
        ]
        kernel = "v2" if trial % 3 == 0 else "jnp"
        got = foldmany.fold_many(folds, n, kernel=kernel)
        assert got == [_want(f, n) for f in folds], (trial, kernel)
