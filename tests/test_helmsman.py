"""Helmsman self-steering-fleet tests (dds_tpu/fleet + shard/rebalance).

Covers the acceptance surface of the autoscaling plane: the controller's
decision tick (hot-streak split, cold-streak merge, hysteresis, cooldown,
migrated-bytes budget, pin override, dead-group promotion), fence-lease
expiry healing an abandoned freeze, crash-safe plan-journal recovery
(deterministic roll-forward/roll-back), deadline-budgeted agent RPCs
(typed DeadlineExceededError, never a hang), live merge + warm-standby
reuse on a constellation, the hardened POST /_reshard route (serialized,
idempotent, honest 409 + Retry-After) with the /_helmsman pin override,
the crash-mid-reshard twin-fleet bit-for-bit test, and the flagship:
a seeded ChaosNet fleet under a migrating Zipf hotspot where the
controller's adaptive shape beats every static shape on
goodput-per-group-hour while the history stays linearizable and the
Watchtower audit stays silent.
"""

import asyncio
import json
import random
import time

import pytest

from dds_tpu.core.chaos import ChaosNet
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.fleet import Helmsman
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.obs.metrics import metrics
from dds_tpu.shard import (
    ReshardAborted,
    ShardMap,
    ShardState,
    build_constellation,
)
from dds_tpu.shard.rebalance import PlanJournal
from tests.test_core import run
from tests.test_linearizability import Recorder, check_atomic_register

pytestmark = pytest.mark.fleet

SECRET = b"intranet-abd-secret"


def constellation(S=2, net=None, seed=7, **kw):
    net = net or InMemoryNet()
    kw.setdefault("n_active", 4)
    kw.setdefault("n_sentinent", 0)
    kw.setdefault("quorum", 3)
    return build_constellation(net, shard_count=S, vnodes_per_group=8,
                               seed=seed, **kw), net


# ----------------------------------------------------------- decision tick


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class _Sim:
    """Hand-cranked signal/action bench for the controller: mutate the
    public fields, call hm.step(), read .actions."""

    def __init__(self, **kw):
        self.clock = _Clock()
        self.census = {"s0": 0, "s1": 0}
        self.alerts = []
        self.shed = 0
        self.ages = {}
        self.moved = 0
        self.busy = False
        self.actions = []
        self.fail_actions = False

        async def act(kind, gid):
            if self.fail_actions:
                raise ReshardAborted(f"injected {kind} failure")
            self.actions.append((kind, gid))
            self.moved += 1024

        kw.setdefault("hot_streak", 3)
        kw.setdefault("cold_streak", 4)
        kw.setdefault("min_ops", 20)
        kw.setdefault("cooldown", 30.0)
        kw.setdefault("max_groups", 4)
        self.hm = Helmsman(
            load_census=lambda: dict(self.census),
            slo_alerts=lambda: list(self.alerts),
            shed_level=lambda: self.shed,
            source_ages=lambda: dict(self.ages),
            split=lambda g: act("split", g),
            merge=lambda g: act("merge", g),
            promote=lambda g: act("promote", g),
            moved_bytes=lambda: self.moved,
            reshard_busy=lambda: self.busy,
            clock=self.clock,
            **kw,
        )

    def load(self, **ops):
        for gid, n in ops.items():
            self.census[gid] = self.census.get(gid, 0) + n


def test_helmsman_splits_hot_group_after_streak_and_cools_down():
    async def go():
        sim = _Sim()
        hm = sim.hm
        sim.alerts = ["write_availability"]
        # two hot ticks: not yet (hysteresis)
        for _ in range(2):
            sim.load(s0=10, s1=90)
            sim.clock.t += 5
            assert await hm.step() is None
        # third consecutive hot tick fires the split on the hot group
        sim.load(s0=10, s1=90)
        sim.clock.t += 5
        assert await hm.step() == "split"
        assert sim.actions == [("split", "s1")]
        # cooldown: still hot, but no second action until it elapses
        sim.load(s0=10, s1=90)
        sim.clock.t += 5
        assert await hm.step() is None
        # a broken streak resets hysteresis: calm tick, then hot again
        sim.clock.t += 40
        sim.alerts = []
        sim.load(s0=50, s1=50)
        assert await hm.step() is None
        sim.alerts = ["write_availability"]
        for _ in range(2):
            sim.load(s0=5, s1=95)
            sim.clock.t += 5
            assert await hm.step() is None  # streak restarted from zero
        sim.load(s0=5, s1=95)
        assert await hm.step() == "split"
        # low-volume ticks never count toward a streak (min_ops gate)
        sim.clock.t += 40
        for _ in range(4):
            sim.load(s1=5)  # only 5 ops: below min_ops
            sim.clock.t += 5
            assert await hm.step() is None
        assert len(sim.actions) == 2

    run(go())


def test_helmsman_merges_cold_group_only_when_calm_and_unshedded():
    async def go():
        # hot_streak=99: the hot side never fires in this sim, so the
        # 98%-share group can't mask the cold-side assertions
        sim = _Sim(cold_streak=3, hot_streak=99)
        hm = sim.hm
        # calm fleet, s1 nearly idle -> merge after the cold streak
        for _ in range(2):
            sim.load(s0=98, s1=2)
            sim.clock.t += 5
            assert await hm.step() is None
        sim.load(s0=98, s1=2)
        assert await hm.step() == "merge"
        assert sim.actions == [("merge", "s1")]
        # shedding forbids merging capacity away: streak never accrues
        sim.clock.t += 40
        sim.shed = 1
        for _ in range(5):
            sim.load(s0=98, s1=2)
            sim.clock.t += 5
            assert await hm.step() is None
        # distress also blocks the cold side
        sim.shed = 0
        sim.alerts = ["latency"]
        for _ in range(5):
            sim.load(s0=98, s1=2)
            sim.clock.t += 5
            assert await hm.step() is None
        assert len(sim.actions) == 1
        # min_groups floor: a 1-group fleet never merges further
        lone = _Sim(cold_streak=1, min_groups=1)
        lone.census = {"s0": 0}
        lone.hm._last_counts = {"s0": 0}
        lone.load(s0=100)
        lone.clock.t += 5
        assert await lone.hm.step() is None

    run(go())


def test_helmsman_budget_pin_busy_and_failed_action():
    async def go():
        sim = _Sim(hot_streak=1, budget_bytes=2000, budget_window=100.0,
                   cooldown=5.0)
        hm = sim.hm
        sim.alerts = ["burn"]

        async def hot_tick():
            sim.load(s0=5, s1=95)
            sim.clock.t += 6  # always past the cooldown
            return await hm.step()

        assert await hot_tick() == "split"        # charges 1024 bytes
        assert await hot_tick() == "split"        # charges 1024 more
        assert hm.budget_remaining() == 0
        assert await hot_tick() is None           # budget exhausted
        assert metrics.value("dds_helmsman_budget_exhausted") == 1
        sim.clock.t += 200                        # window slides clear
        assert await hot_tick() == "split"
        # pinned: shape frozen even under distress
        hm.pin()
        assert await hot_tick() is None
        assert hm.report()["pinned"]
        hm.unpin()
        # a reshard already holding the lock defers the tick
        sim.busy = True
        assert await hot_tick() is None
        sim.busy = False
        # a failed action cools down instead of hammering the same plan
        n = len(sim.actions)
        sim.fail_actions = True
        assert await hot_tick() is None
        assert any(r["action"] == "split_failed" for r in hm.history)
        sim.fail_actions = False
        sim.load(s0=5, s1=95)
        sim.clock.t += 1  # inside the failure cooldown
        assert await hm.step() is None
        assert len(sim.actions) == n

    run(go())


def test_helmsman_promotes_dead_group_even_when_pinned():
    async def go():
        sim = _Sim(heartbeat_timeout=15.0, cooldown=10.0)
        hm = sim.hm
        hm.pin()  # a pin must never turn a crash into an unserved keyspace
        sim.load(s0=50, s1=50)
        sim.ages = {"s0": 0.2, "s1": 40.0}  # s1's shipper went silent
        assert await hm.step() == "promote"
        assert sim.actions == [("promote", "s1")]
        # the takeover is not re-launched while the first one settles
        sim.clock.t += 5
        assert await hm.step() is None
        assert sim.actions == [("promote", "s1")]
        # an unknown gid (not in the census) never triggers a takeover
        sim.ages = {"ghost": 99.0}
        sim.clock.t += 60
        assert await hm.step() is None
        # a failed promotion is recorded, not raised
        sim.ages = {"s0": 50.0}
        sim.fail_actions = True
        sim.clock.t += 60
        assert await hm.step() is None
        assert any(r["action"] == "promote_failed" for r in hm.history)

    run(go())


def test_helmsman_from_config_and_report_shape():
    from dds_tpu.utils.config import HelmsmanConfig

    cfg = HelmsmanConfig(hot_streak=7, budget_bytes=123, pin=True)
    hm = Helmsman.from_config(cfg, load_census=lambda: {})
    assert hm.hot_streak == 7 and hm.budget_bytes == 123 and hm.pinned
    rep = hm.report()
    for k in ("pinned", "ticks", "cooldown_remaining",
              "budget_remaining_bytes", "recent"):
        assert k in rep


# ------------------------------------------------------------- fence lease


def test_fence_lease_expires_back_to_committed_map():
    clk = _Clock()
    m1 = ShardMap.build(["s0", "s1"], 8).sign(SECRET)
    st = ShardState("s1", m1, SECRET, clock=clk)
    m2 = m1.split("s1", "s2").sign(SECRET)
    before = metrics.value("dds_shard_lease_expired_total",
                           shard="s1") or 0
    st.install(m2, lease=5.0)
    assert st.leased and st.epoch == m2.epoch
    assert 0 < st.lease_remaining() <= 5.0
    # renewal pushes the horizon out
    clk.t += 4
    st.install(m2, lease=5.0)
    clk.t += 4  # 8s after the first install: only alive because renewed
    assert st.leased and st.epoch == m2.epoch
    # the driver dies: expiry heals the state back to the committed map
    clk.t += 2
    assert not st.leased
    assert st.epoch == m1.epoch and st.map is m1
    assert (metrics.value("dds_shard_lease_expired_total", shard="s1")
            or 0) == before + 1
    # a committed install never reverts, no matter how long
    st.install(m2, lease=5.0)
    st.install(m2)  # commit
    clk.t += 1000
    assert st.epoch == m2.epoch and not st.leased


# ------------------------------------------------------------ plan journal


def test_plan_journal_atomic_roundtrip(tmp_path):
    j = PlanJournal(str(tmp_path))
    assert j.load() is None
    j.write({"kind": "split", "phase": "freeze"})
    assert PlanJournal(str(tmp_path)).load() == {"kind": "split",
                                                 "phase": "freeze"}
    # corrupt file: warn-and-None, never raise
    j.path.write_text("{nope")
    assert j.load() is None
    j.clear()
    assert not j.path.exists()
    mem = PlanJournal(None)
    mem.write({"a": 1})
    assert mem.load() == {"a": 1}
    mem.clear()
    assert mem.load() is None


def _journal_plan(kind, source, targets, old, new, phase):
    return {"kind": kind, "source": source, "targets": targets,
            "old": old.to_wire(), "new": new.to_wire(), "phase": phase}


def test_recover_rolls_back_before_commit(tmp_path):
    async def go():
        const, net = constellation(S=2, journal_dir=str(tmp_path),
                                   fence_lease=30.0)
        old = const.manager.current()
        new = old.merge("s1").sign(SECRET)
        # a crashed driver froze both participants and died mid-stream
        for gid in ("s0", "s1"):
            const.group(gid).state.install(new, lease=30.0)
        PlanJournal(str(tmp_path)).write(
            _journal_plan("merge", "s1", ["s0"], old, new, "stream"))
        assert await const.rebalancer.recover(const.group) == "rollback"
        # the old map is the truth again, committed (no lease), everywhere
        for gid in ("s0", "s1"):
            st = const.group(gid).state
            assert st.epoch == old.epoch and not st.leased
        assert const.manager.epoch == old.epoch
        assert PlanJournal(str(tmp_path)).load() is None
        await const.stop()

    run(go())


def test_recover_rolls_forward_from_commit(tmp_path):
    async def go():
        const, net = constellation(S=2, journal_dir=str(tmp_path),
                                   fence_lease=30.0)
        old = const.manager.current()
        key = next(k for k in (f"RF{i}" for i in range(64))
                   if old.owner(k) == "s0")
        await const.router.write_set(key, ["kept"])
        new = old.merge("s1").sign(SECRET)
        # the crashed driver got past the commit point: participants hold
        # committed new-map fencing, only activation is missing
        for gid in ("s0", "s1"):
            const.group(gid).state.install(new)
        PlanJournal(str(tmp_path)).write(
            _journal_plan("merge", "s1", ["s0"], old, new, "commit"))
        seen = []
        const.rebalancer.on_activate = seen.append
        assert await const.rebalancer.recover(const.group) == "rollforward"
        assert const.manager.epoch == new.epoch
        assert seen and seen[0].epoch == new.epoch  # broadcast ran
        assert PlanJournal(str(tmp_path)).load() is None
        # the fleet serves under the recovered map
        assert await const.router.fetch_set(key) == ["kept"]
        await const.stop()

    run(go())


# ------------------------------------------------------- deadline-budgeted


def test_agent_rpc_deadline_exceeds_typed_never_hangs():
    from dds_tpu.fabric.remote import AgentClient, AgentTimeout
    from dds_tpu.utils.retry import DeadlineExceededError

    async def go():
        net = InMemoryNet()  # nobody is listening at "meridian-ctl"
        cli = AgentClient(net, "probe", timeout=0.05, budget=0.2)
        smap = ShardMap.build(["s0"], 4).sign(SECRET)
        t0 = time.monotonic()
        with pytest.raises((AgentTimeout, DeadlineExceededError)):
            await cli.install("meridian-ctl", smap)
        assert time.monotonic() - t0 < 2.0  # budget-bounded, not hung
        # a caller-scoped Deadline wins over the client default
        from dds_tpu.utils.retry import Deadline

        t0 = time.monotonic()
        with pytest.raises((AgentTimeout, DeadlineExceededError)):
            await cli.activate("meridian-ctl", smap,
                              deadline=Deadline(0.08))
        assert time.monotonic() - t0 < 1.0

    run(go())


# ------------------------------------------------------- live merge + reuse


def test_constellation_merge_end_to_end_and_standby_reuse():
    async def go():
        const, net = constellation(S=2)
        r = const.router
        keys = [f"MRG-{i}" for i in range(24)]
        for k in keys:
            await r.write_set(k, [k])
        assert {r.owner(k) for k in keys} == {"s0", "s1"}
        receivers = await const.merge("s1")
        assert receivers == ["s0"]
        assert const.gids == ["s0"]
        assert [g.gid for g in const.standbys] == ["s1"]
        assert const.manager.epoch == 2
        for k in keys:
            assert await r.fetch_set(k) == [k]  # nothing lost in the fold
        await net.quiesce()
        # the victim was pruned: it holds none of the migrated keys
        victim = const.standbys[0]
        for n in victim.replicas.values():
            for k in keys:
                assert n.repository.get(k, (None, None))[1] is None
        assert const.rebalancer.moved_bytes_total > 0
        # the next split REUSES the warm standby instead of building new
        g = await const.split("s0")
        assert g.gid == "s1" and not const.standbys
        assert const.manager.epoch == 3
        assert {r.owner(k) for k in keys} == {"s0", "s1"}
        for k in keys:
            assert await r.fetch_set(k) == [k]
        await const.stop()

    run(go())


# -------------------------------------------------------- hardened /_reshard


def test_reshard_route_serialized_idempotent_and_pin_override():
    from dds_tpu.http.miniserver import http_request_full
    from dds_tpu.run import ConstellationReshard

    async def go():
        const, net = constellation(S=2)
        ctl = ConstellationReshard(const)
        gate = asyncio.Event()
        orig_split = ctl.split

        async def gated_split(source, target=None):
            await gate.wait()
            return await orig_split(source, target)

        ctl.split = gated_split
        hm = Helmsman(load_census=lambda: {})
        server = DDSRestServer(
            const.router, ProxyConfig(port=0, reshard_route_enabled=True),
            reshard=ctl, helmsman=hm,
        )
        await server.start()
        port = server.cfg.port

        async def post(obj):
            return await http_request_full(
                "127.0.0.1", port, "POST", "/_reshard",
                json.dumps(obj).encode(), timeout=30.0)

        try:
            first = asyncio.ensure_future(post({"source": "s1"}))
            second = asyncio.ensure_future(post({"source": "s1"}))
            await asyncio.sleep(0.1)
            assert not first.done() and not second.done()
            # a DIFFERENT plan is refused honestly while one is in flight
            st, hdrs, body = await post({"action": "merge", "source": "s0"})
            assert st == 409
            d = json.loads(body)
            assert d["busy"] == {"action": "split", "source": "s1",
                                 "target": None}
            assert int(hdrs["retry-after"]) >= 1
            gate.set()
            (st1, _, b1), (st2, _, b2) = await asyncio.gather(first, second)
            # the identical repeat attached to the SAME plan: one epoch
            # bump, both callers see the same result
            assert st1 == 200 and st2 == 200 and b1 == b2
            assert json.loads(b1)["epoch"] == 2
            assert const.manager.epoch == 2
            assert sorted(json.loads(b1)["groups"]) == ["s0", "s1", "s2"]
            # COMPLETED idempotency: replaying a split whose target is
            # already in the map answers the map, moves nothing
            st, _, body = await post({"source": "s1", "target": "s2"})
            assert st == 200 and json.loads(body)["idempotent"]
            assert const.manager.epoch == 2
            # merge through the route works and is itself replay-safe
            st, _, body = await post({"action": "merge", "source": "s2"})
            assert st == 200 and json.loads(body)["epoch"] == 3
            st, _, body = await post({"action": "merge", "source": "s2"})
            assert st == 200 and json.loads(body)["idempotent"]
            # validation: bad action / missing source
            st, _, _ = await post({"action": "explode", "source": "s1"})
            assert st == 400
            st, _, _ = await post({"action": "split"})
            assert st == 400
            # /_helmsman pin override round-trips and shows in /health
            st, body = await http_request(
                "127.0.0.1", port, "POST", "/_helmsman",
                json.dumps({"pin": True}).encode(), timeout=10.0)
            assert st == 200 and json.loads(body)["pinned"]
            st, body = await http_request(
                "127.0.0.1", port, "GET", "/health", timeout=10.0)
            assert json.loads(body)["helmsman"]["pinned"]
            st, _, body = await http_request_full(
                "127.0.0.1", port, "POST", "/_helmsman",
                json.dumps({"pin": False}).encode(), timeout=10.0)
            assert st == 200 and not json.loads(body)["pinned"]
        finally:
            await server.stop()
            await const.stop()

    run(go())


# ------------------------------------------------------ crash-safe reshard


@pytest.mark.chaos
def test_crash_mid_split_and_mid_merge_twin_fleet_bit_for_bit(tmp_path):
    """Acceptance (ISSUE 15): a group process killed mid-split (stream
    phase) and mid-merge is detected, the plan resolves deterministically
    (rollback here — the crash lands before the commit point), the dead
    participant's fence lease expires back to serving, and post-recovery
    SumAll/Search answers are bit-for-bit equal to an undisturbed twin
    fleet. The 'kill' is total: the group's replicas drop off the net AND
    its (shared) state handle refuses installs, so the abort's rollback
    cannot reach it — only the lease can heal it."""
    from dds_tpu.models import HEKeys
    from dds_tpu.utils.config import SearchConfig

    he = HEKeys.generate(paillier_bits=512, rsa_bits=512)
    pk = he.psse.public
    vals = [(7, "red"), (21, "blue"), (301, "red"),
            (44, "green"), (5, "red"), (600, "blue")]
    rows = [[str(pk.encrypt(v)), c] for v, c in vals]  # ONE encryption

    async def build(tag):
        net = ChaosNet(InMemoryNet(), seed=41)
        const, _ = constellation(
            S=2, net=net, seed=5, manifest_timeout=0.4, ack_timeout=0.3,
            fence_lease=1.0, journal_dir=str(tmp_path / tag))
        server = DDSRestServer(const.router, ProxyConfig(
            port=0, crypto_backend="cpu",
            search=SearchConfig(enabled=True, write_ingest=True,
                                ingest_window=0.001)))
        await server.start()
        for row in rows:
            st, _ = await http_request(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": row}).encode(), timeout=10.0)
            assert st == 200
        return net, const, server

    async def results(server):
        st, body = await http_request(
            "127.0.0.1", server.cfg.port, "GET",
            f"/SumAll?position=0&nsqr={pk.nsquare}", timeout=30.0)
        assert st == 200
        total = json.loads(body)["result"]
        st, body = await http_request(
            "127.0.0.1", server.cfg.port, "POST", "/SearchEq?position=1",
            json.dumps({"value": "red"}).encode(), timeout=30.0)
        assert st == 200
        return total, sorted(json.loads(body)["keyset"])

    def kill_at_stream(net, reb, state, replicas):
        """At stream entry: the group's process dies — frames drop and
        the shared state handle stops answering installs."""
        orig_enter, orig_install = reb._enter, state.install

        def dead_install(m, force=False, lease=0.0):
            raise RuntimeError("group process is dead")

        def spy(phase, **info):
            orig_enter(phase, **info)
            if phase == "stream":
                net.partition(replicas)
                state.install = dead_install

        reb._enter = spy

        def revive():
            reb._enter = orig_enter
            state.install = orig_install
            net.heal_all()

        return revive

    async def go():
        netA, A, srvA = await build("A")
        netB, B, srvB = await build("B")
        try:
            old = A.manager.current()

            # ---- killed mid-SPLIT: the stream-phase TARGET dies
            with pytest.raises(ReshardAborted):
                # arm inside the same block: the target group only exists
                # once the split acquires it, but its gid is deterministic
                revive = None
                try:
                    orig_acquire = A._acquire_standby

                    def acquiring(gid=None):
                        g = orig_acquire(gid)
                        nonlocal revive
                        revive = kill_at_stream(
                            netA, A.rebalancer, g.state, g.all_replicas())
                        return g

                    A._acquire_standby = acquiring
                    await A.split("s1")
                finally:
                    A._acquire_standby = orig_acquire
            assert A.manager.current() is old
            assert A.manager.state == "stable"
            # the dead target still holds the provisional freeze: only
            # its fence lease can heal it back to the committed map
            standby = A.standbys[0]
            assert standby.gid == "s2" and standby.state.leased
            await asyncio.sleep(1.2)
            assert not standby.state.leased
            assert standby.state.epoch == old.epoch
            revive()

            # ---- killed mid-MERGE: the stream-phase RECEIVER dies
            s0 = A.group("s0")
            revive = kill_at_stream(netA, A.rebalancer, s0.state,
                                    s0.all_replicas())
            with pytest.raises(ReshardAborted):
                await A.merge("s1")
            assert A.manager.current() is old
            assert A.gids == ["s0", "s1"]  # the victim was never retired
            assert s0.state.leased  # the rollback could not reach it
            await asyncio.sleep(1.2)
            assert not s0.state.leased and s0.state.epoch == old.epoch
            revive()
            await netA.quiesce()

            # both plans resolved: no journal entry survives
            assert PlanJournal(str(tmp_path / "A")).load() is None

            # ---- bit-for-bit vs the undisturbed twin
            got, want = await results(srvA), await results(srvB)
            assert got == want
            assert he.psse.decrypt(int(got[0])) == sum(v for v, _ in vals)
            assert got[1]  # the search really matched rows
        finally:
            netA.heal_all()
            for s in (srvA, srvB):
                await s.stop()
            for c in (A, B):
                await c.stop()

    run(go())


# ----------------------------------------------------- flagship: autoscale


@pytest.mark.chaos
def test_adaptive_fleet_beats_static_shapes_on_goodput_per_group_hour():
    """Acceptance (ISSUE 15): under a seeded ChaosNet and an open-loop
    Zipf-style load whose hotspot migrates mid-run, the Helmsman-steered
    fleet splits the hot group onto a standby, merges cooled capacity
    back, and beats EVERY static shape S in {1, 2, 4} on goodput per
    group-hour over the identical arrival schedule — while a concurrent
    write history linearizes and a Watchtower with per-group geometry
    reports zero quorum-intersection / tag-monotonicity violations.

    Capacity model: each serving group has LANES concurrent service
    lanes (SERVICE seconds per op); an op is GOOD iff it finishes within
    SLO of its scheduled arrival. The model prices fleet shape the way
    the paper's cost model prices migration: groups you keep are paid
    for whether the hotspot uses them or not."""
    from dds_tpu.core.chaos import LinkFaults
    from dds_tpu.obs.watchtower import Watchtower
    from dds_tpu.utils.retry import Deadline, RetryPolicy, retry_deadline
    from dds_tpu.utils.trace import tracer

    LANES, SERVICE, SLO = 4, 0.004, 0.12
    RATE, P_HOT, TAIL_RATE = 1600.0, 0.9, 600.0
    PHASE, TAIL = 1.0, 0.9

    # ---- hot-key selection: a genuine arc hotspot — the same 6 keys are
    # hot under EVERY fleet shape (they cluster on one group's arc in the
    # 2-group AND 4-group rings), and a midpoint split divides them
    map2 = ShardMap.build(["s0", "s1"], 8)
    map4 = ShardMap.build(["s0", "s1", "s2", "s3"], 8)
    split2 = map2.split("s1", "s2")

    def pick_hot(owner2, splitmap, new_gid):
        import collections as C

        cand = [f"LOAD-{i}" for i in range(400)
                if map2.owner(f"LOAD-{i}") == owner2]
        dom = C.Counter(map4.owner(k) for k in cand).most_common(1)[0][0]
        cand = [k for k in cand if map4.owner(k) == dom]
        stay = [k for k in cand if splitmap.owner(k) == owner2][:3]
        move = [k for k in cand if splitmap.owner(k) == new_gid][:3]
        assert len(stay) == 3 and len(move) == 3
        return stay + move

    hot_a = pick_hot("s1", split2, "s2")
    hot_b = pick_hot("s0", split2.split("s0", "s3"), "s3")
    uniform = [f"U-{i}" for i in range(52)]
    universe = uniform + hot_a + hot_b

    # ---- one seeded open-loop schedule, shared by every run
    rng = random.Random(0xF1EE7)
    sched = []
    t = 0.0
    while t < 2 * PHASE:
        t += 1.0 / RATE
        hot = hot_a if t < PHASE else hot_b
        key = (hot[rng.randrange(len(hot))] if rng.random() < P_HOT
               else universe[rng.randrange(len(universe))])
        sched.append((t, key))
    while t < 2 * PHASE + TAIL:  # cool tail: load concentrates back on A
        t += 1.0 / TAIL_RATE
        key = (hot_a[rng.randrange(len(hot_a))] if rng.random() < 0.7
               else universe[rng.randrange(len(universe))])
        sched.append((t, key))

    _POLICY = RetryPolicy(base=0.01, multiplier=2.0, max_delay=0.08)

    async def writer(router, rec, key, wid, n, seed):
        w_rng = random.Random(seed)
        for i in range(n):
            value = [f"w{wid}-{i}"]
            t0 = time.monotonic()
            dl = Deadline(10.0)
            await retry_deadline(
                lambda: router.write_set(key, value, deadline=dl),
                dl, _POLICY, rng=w_rng, retry_on=(Exception,),
            )
            rec.record("write", f"w{wid}-{i}", t0, time.monotonic())
            await asyncio.sleep(w_rng.uniform(0.01, 0.04))

    async def run_shape(S, adaptive):
        net = ChaosNet(InMemoryNet(), seed=99)
        net.default_faults = LinkFaults(jitter=0.002)  # seeded chaos
        const, _ = constellation(S=S, net=net, seed=13)
        r = const.router
        for k in universe:
            await r.write_set(k, [k])
        lanes: dict = {}
        counts: dict = {}
        stats = {"good": 0, "done": 0, "backlog": 0, "integral": 0.0}
        t0 = time.monotonic()

        async def op(due, key):
            delay = due - (time.monotonic() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            stats["backlog"] += 1
            gid = r.owner(key)
            counts[gid] = counts.get(gid, 0) + 1
            sem = lanes.setdefault(gid, asyncio.Semaphore(LANES))
            async with sem:
                await asyncio.sleep(SERVICE)
            stats["backlog"] -= 1
            stats["done"] += 1
            if (time.monotonic() - t0) - due <= SLO:
                stats["good"] += 1

        hm = None
        if adaptive:
            hm = Helmsman(
                load_census=lambda: dict(counts),
                slo_alerts=lambda: (["goodput_burn"]
                                    if stats["backlog"] > 80 else []),
                split=const.split,
                merge=const.merge,
                moved_bytes=lambda: const.rebalancer.moved_bytes_total,
                reshard_busy=const.rebalancer.lock.locked,
                hot_streak=2, cold_streak=3, hot_share=0.55,
                cold_share=0.15, min_ops=15, cooldown=0.35,
                max_groups=4, budget_bytes=1 << 30,
            )
        stop = asyncio.Event()

        async def sample():  # group-seconds you pay for, 20ms resolution
            while not stop.is_set():
                stats["integral"] += len(const.groups) * 0.02
                await asyncio.sleep(0.02)

        ticklog = []

        async def steer():  # the controller tick; never blocks sampling
            while not stop.is_set():
                await hm.step()
                ticklog.append((round(time.monotonic() - t0, 2),
                                stats["backlog"],
                                dict(hm._cold_streaks),
                                {g: round(s, 2)
                                 for g, s in hm._shares.__self__._last_counts.items()}))
                await asyncio.sleep(0.1)

        sampler = asyncio.ensure_future(sample())
        steerer = (asyncio.ensure_future(steer()) if hm is not None
                   else None)
        tasks = [asyncio.ensure_future(op(due, key)) for due, key in sched]
        side = []
        rec = Recorder()
        if adaptive:
            wkey_a = hot_a[0]
            wkey_u = next(k for k in uniform if map2.owner(k) == "s0")
            side = [asyncio.ensure_future(
                        writer(r, rec, wkey_a, 0, 18, seed=31)),
                    asyncio.ensure_future(
                        writer(r, rec, wkey_u, 1, 18, seed=32))]
        await asyncio.gather(*tasks, *side)
        stop.set()
        await sampler
        if steerer is not None:
            await steerer
        if adaptive:
            check_atomic_register(
                [o for o in rec.ops if o["kind"] == "write"])
            assert await r.fetch_set(wkey_a) == ["w0-17"]
        # every preloaded key survived whatever resharding happened
        for k in universe[::7]:
            assert await r.fetch_set(k) == [k]
        history = list(hm.history) if hm else []
        await const.stop()
        score = stats["good"] / max(stats["integral"], 1e-9)
        return score, stats, history, ticklog

    async def go():
        wt = Watchtower(quorum_size=3, n_replicas=4)
        wt.configure(group_geometry={f"s{i}": (3, 4) for i in range(6)})
        wt.attach(tracer)
        try:
            adaptive_score, a_stats, history, tl = await run_shape(2, True)
            bad = [v for v in wt.verdicts() if v.invariant in
                   ("quorum_intersection", "tag_monotonicity")]
            assert not bad, bad
        finally:
            wt.detach()
        done = {r["action"] for r in history}
        assert "split_done" in done, history  # the hot group really split
        assert "merge_done" in done, (history, tl[-12:])
        scores = {}
        for S in (1, 2, 4):
            scores[S], _, _, _ = await run_shape(S, False)
        for S, s in scores.items():
            assert adaptive_score > s, (
                f"adaptive {adaptive_score:.1f} <= static S={S} {s:.1f} "
                f"goodput/group-s (adaptive stats: {a_stats})"
            )

    run(go())


# ----------------------------------------------------------------- sentry


def test_sentry_check_parses_autoscale_records(tmp_path):
    from benchmarks.sentry import _check_autoscale_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "autoscale goodput",
        "value": 237.6, "unit": "good/group-s", "vs_baseline": 1.516,
        "detail": {
            "static_score": 156.7, "splits": 2, "merges": 1,
            "moved_bytes": 2745, "open_loop": True,
        },
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_autoscale_records(str(tmp_path)) == {"rows": 1}
    # closed-loop or action-less records are malformed: the score is only
    # comparable when measured from scheduled arrivals, and a row that
    # cannot say what the controller DID cannot justify its group-seconds
    for broken in (
        dict(good, value=-1),
        dict(good, detail=dict(good["detail"], open_loop=False)),
        dict(good, detail=dict(good["detail"], splits=None)),
        dict(good, detail={"static_score": 1.0}),
    ):
        (bench / "results.json").write_text(json.dumps([good, broken]))
        with pytest.raises(ValueError):
            _check_autoscale_records(str(tmp_path))
    # other record families are ignored by this checker
    (bench / "results.json").write_text(
        json.dumps([{"metric": "overload goodput interactive", "value": -1}])
    )
    assert _check_autoscale_records(str(tmp_path)) == {"rows": 0}
