"""Aegis recovery-plane tests: Byzantine-verified state transfer, Merkle
anti-entropy convergence, and crash-safe authenticated snapshots — all
exercised under seeded ChaosNet schedules where the scenario calls for an
adversarial network.

Acceptance paths (ISSUE 3):
- a recovered replica seeded by a Byzantine spare holds ZERO forged
  entries (the digest quorum rejects them);
- a snapshot file flipped by one byte is quarantined at boot, never
  loaded and never allowed to crash run.launch;
- anti-entropy converges a stale rejoined replica to the quorum state
  without any client reads.
"""

import asyncio
import random

import pytest

from dds_tpu.core import messages as M
from dds_tpu.core.antientropy import MerkleIndex
from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.supervisor import BFTSupervisor, SupervisorConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.utils import sigs
from dds_tpu.utils.trace import tracer

pytestmark = pytest.mark.recovery


def run(coro):
    return asyncio.run(coro)


class Cluster:
    """In-process cluster with optional seeded ChaosNet fabric."""

    def __init__(self, n_active=7, n_sentinent=2, quorum=5, chaos_seed=None,
                 awake_timeout=0.5, crashed_timeout=1.0):
        inner = InMemoryNet()
        self.chaos = None
        if chaos_seed is not None:
            self.chaos = ChaosNet(inner, seed=chaos_seed)
            self.net = self.chaos
        else:
            self.net = inner
        self.rcfg = ReplicaConfig(quorum_size=quorum)
        all_addrs = [f"replica-{i}" for i in range(n_active + n_sentinent)]
        self.active = all_addrs[:n_active]
        self.sentinent = all_addrs[n_active:]
        self.replicas = {
            a: BFTABDNode(a, all_addrs, "supervisor", self.net, self.rcfg)
            for a in all_addrs
        }
        for a in self.sentinent:
            self.replicas[a].behavior = "sentinent"
        self.supervisor = BFTSupervisor(
            "supervisor",
            self.active,
            self.sentinent,
            self.net,
            SupervisorConfig(
                quorum_size=quorum,
                proactive_recovery_enabled=False,
                sentinent_awake_timeout=awake_timeout,
                crashed_recovery_timeout=crashed_timeout,
                manifest_timeout=1.0,
            ),
            redeploy=self._redeploy,
            rng=random.Random(3),
        )
        self.client = AbdClient(
            "proxy-0", self.net, self.active,
            AbdClientConfig(request_timeout=1.0),
        )
        self.client.replicas._rng = random.Random(7)

    async def _redeploy(self, endpoint):
        self.replicas[endpoint] = BFTABDNode(
            endpoint, list(self.replicas), "supervisor", self.net, self.rcfg
        )

    async def quiesce(self):
        await self.net.quiesce()

    async def write(self, value):
        key = sigs.key_from_set(value)
        await self.client.write_set(key, value)
        return key

    def poison_spare(self, spare_name, real_key=None):
        """Make a spare's State forged: a fabricated high-tag key (also
        inflating its freshness rank so it WILL be chosen as seeder) plus,
        when given, a tampered value under a real key's true tag."""
        spare = self.replicas[spare_name]
        spare._store("FORGED-KEY", M.ABDTag(1 << 20, "trudy"), ["evil", 666])
        if real_key is not None and real_key in spare.repository:
            tag, _ = spare.repository[real_key]
            spare._store(real_key, tag, ["tampered"])


def honest_state(cluster, replicas=None):
    """{key: (tag, value)} attested identically by a majority of the given
    replicas — the ground truth a recovered node must converge to."""
    from collections import Counter

    names = replicas or cluster.active
    votes = Counter()
    for name in names:
        node = cluster.replicas[name]
        for k, (t, v) in node.repository.items():
            if MerkleIndex._tracked(t, v):
                votes[(k, t, sigs.canonical(v))] += 1
    out = {}
    for (k, t, cv), n in votes.items():
        if n > len(names) // 2:
            out[k] = (t, cv)
    return out


# --------------------------------------------------- verified state transfer


def test_byzantine_spare_forged_state_rejected_under_chaos():
    """Acceptance: one Byzantine spare serves a forged State during
    recovery under a seeded ChaosNet schedule; the recovered replica's
    repository contains zero forged entries."""

    async def go():
        c = Cluster(chaos_seed=42)
        # mild asymmetric jitter on a few quorum legs: the schedule is
        # active (trace non-empty) but deliveries all complete
        c.chaos.set_dest("replica-3", LinkFaults(delay=0.002, jitter=0.003))
        c.chaos.set_dest("replica-5", LinkFaults(delay=0.001, jitter=0.002))
        keys = [await c.write([i, f"row-{i}"]) for i in range(6)]
        await c.quiesce()
        # replica-8 is Byzantine: forged key + tampered value under a real
        # tag; its inflated tag seq also makes it the freshest-ranked spare
        c.poison_spare("replica-8", real_key=keys[0])
        await c.supervisor.recover("replica-0")
        await c.quiesce()
        r0 = c.replicas["replica-0"]
        assert r0.behavior == "sentinent"
        # the forged entry and the tampered value are both rejected
        assert "FORGED-KEY" not in r0.repository
        got = r0.repository.get(keys[0], (None, None))[1]
        assert got != ["tampered"]
        # nothing in the recovered repository deviates from the honest
        # majority state: zero poisoned keys
        truth = honest_state(c)
        for k, (t, v) in r0.repository.items():
            if MerkleIndex._tracked(t, v):
                assert k in truth and truth[k][1] == sigs.canonical(v)
        assert len(c.chaos.trace) > 0  # the chaos schedule actually ran

    run(go())


def test_verified_transfer_streams_chunks():
    """A repository larger than state_chunk_keys streams as multiple
    StateChunk frames and still reseeds byte-identically."""

    async def go():
        c = Cluster()
        c.supervisor.cfg.state_chunk_keys = 4
        keys = [await c.write([i, "v"]) for i in range(11)]
        await c.quiesce()
        await c.supervisor.recover("replica-0")
        await c.quiesce()
        r0 = c.replicas["replica-0"]
        for k in keys:
            assert r0.repository.get(k, (None, None))[1] == \
                c.replicas["replica-1"].repository[k][1]

    run(go())


def test_freshest_spare_preferred_and_seeder_traced():
    """Satellite: the supervisor seeds from the spare with the freshest
    repository (not a random one), and records the chosen seeder in the
    recovery trace span."""

    async def go():
        c = Cluster()
        key = await c.write([1, "x"])
        await c.quiesce()
        # replica-7 is stale (wiped); replica-8 observed the write
        c.replicas["replica-7"]._install_repository({})
        assert c.replicas["replica-8"].repository  # sanity: spare has data
        await c.supervisor.recover("replica-0")
        await c.quiesce()
        active = [a for a, _ in c.supervisor.active]
        assert "replica-8" in active        # freshest spare promoted
        assert "replica-7" not in active    # stale spare left alone
        seeders = [e for e in tracer.events("supervisor.seeder")
                   if e.meta.get("victim") == "replica-0"]
        assert seeders and seeders[-1].meta["seeder"] == "replica-8"

    run(go())


def test_verified_transfer_off_falls_back_to_legacy_sleep():
    async def go():
        c = Cluster()
        c.supervisor.cfg.verified_transfer = False
        key = await c.write([9, "legacy"])
        await c.quiesce()
        await c.supervisor.recover("replica-0")
        await c.quiesce()
        assert c.replicas["replica-0"].repository[key][1] == [9, "legacy"]
        assert c.replicas["replica-0"].behavior == "sentinent"

    run(go())


# ------------------------------------------------------- Merkle anti-entropy


def test_merkle_index_incremental_matches_rebuild():
    rng = random.Random(5)
    idx = MerkleIndex()
    repo = {}
    for step in range(300):
        k = f"key-{rng.randrange(40)}"
        if rng.random() < 0.15 and k in repo:
            # a delete is a None write under a REAL tag: stays tracked
            tag = M.ABDTag(repo[k][0].seq + 1, "r1")
            repo[k] = (tag, None)
        else:
            tag = M.ABDTag(rng.randrange(1, 1000), f"r{rng.randrange(3)}")
            repo[k] = (tag, [rng.randrange(100), "v"])
        idx.update(k, *repo[k])
    fresh = MerkleIndex()
    fresh.rebuild(repo)
    assert idx.root() == fresh.root()
    assert idx.bucket_digests() == fresh.bucket_digests()
    # the implicit _state() default is excluded from tracking
    idx.update("phantom", M.ABDTag(0, "r0"), None)
    assert idx.root() == fresh.root()


def test_antientropy_converges_stale_rejoined_replica_without_reads():
    """Acceptance: a stale rejoined replica converges to the quorum state
    through anti-entropy alone — no client read ever touches the keys."""

    async def go():
        c = Cluster()
        keys = [await c.write([i, f"data-{i}"]) for i in range(12)]
        await c.quiesce()
        stale = c.replicas["replica-1"]
        stale._install_repository({})  # snapshot-restored-from-nothing rejoiner
        peer = c.replicas["replica-2"]
        assert stale.merkle.root() != peer.merkle.root()
        repaired = 0
        for _ in range(3):  # bounded rounds; one should suffice
            repaired += await stale.antientropy.sync_once("replica-2")
            if stale.merkle.root() == peer.merkle.root():
                break
        assert repaired == len(keys)
        # byte-identical convergence: same tags, same values
        assert stale.merkle.root() == peer.merkle.root()
        for k in keys:
            assert stale.repository[k] == peer.repository[k]

    run(go())


def test_antientropy_in_sync_round_is_cheap_and_counted():
    async def go():
        c = Cluster()
        await c.write([1, "a"])
        await c.quiesce()
        node = c.replicas["replica-0"]
        assert await node.antientropy.sync_once("replica-1") == 0
        stats = node.antientropy.stats()
        assert stats["rounds"] == 1 and stats["divergent_buckets"] == 0
        assert stats["last_sync_age"] is not None

    run(go())


def test_recovery_under_chaos_partition_then_antientropy_convergence():
    """The end-to-end schedule: partition + crash mid-workload under a
    seeded ChaosNet, Byzantine spare, verified re-seed, heal, anti-entropy
    — the recovered replica converges to the quorum state with zero
    poisoned keys and no client reads after the heal."""

    async def go():
        c = Cluster(chaos_seed=1234, awake_timeout=0.3, crashed_timeout=1.0)
        keys = [await c.write([i, "pre"]) for i in range(4)]
        await c.quiesce()
        # partition one active replica away mid-workload (5 reachable = q)
        part = c.chaos.partition(["replica-6"])
        # crash the victim (goes silent, like a Trudy crash)
        c.net.send("trudy", "replica-0", M.Crash())
        await c.quiesce()
        # workload continues against the damaged cluster; a draw of the
        # crashed coordinator times out, so retry like the proxy would
        from dds_tpu.core.errors import ByzantineError

        for i in range(4, 7):
            value = [i, "mid"]
            for _ in range(8):
                try:
                    keys.append(await c.write(value))
                    break
                except (ByzantineError, asyncio.TimeoutError):
                    continue
            else:
                raise AssertionError("quorum never completed mid-partition")
        await c.quiesce()
        # Byzantine spare ready to poison the recovery seed
        c.poison_spare("replica-8", real_key=keys[0])
        # suspicion quorum -> recovery (crashed path: redeploy + reseed)
        for i in range(1, 6):
            c.net.send(f"replica-{i}", "supervisor",
                       M.Suspect("replica-0", sigs.generate_nonce()))
        for _ in range(60):
            await asyncio.sleep(0.05)
            await c.quiesce()
            if "replica-0" in c.supervisor.sentinent:
                break
        assert "replica-0" in c.supervisor.sentinent
        r0 = c.replicas["replica-0"]
        assert "FORGED-KEY" not in r0.repository  # zero forged entries

        # heal the partition; no client reads from here on
        part.heal()
        truth = honest_state(c, ["replica-1", "replica-2", "replica-3",
                                 "replica-4", "replica-5"])
        for node_name in ("replica-0", "replica-6"):
            node = c.replicas[node_name]
            for peer in ("replica-1", "replica-2", "replica-3"):
                await node.antientropy.sync_once(peer)
                await c.quiesce()
        # every written key converges byte-identically on the rejoiners
        for node_name in ("replica-0", "replica-6"):
            node = c.replicas[node_name]
            for k in keys:
                assert k in truth
                tag, cv = truth[k]
                assert node.repository.get(k, (None, None))[0] == tag
                assert sigs.canonical(node.repository[k][1]) == cv
            # and zero poisoned keys anywhere in the repository
            for k, (t, v) in node.repository.items():
                if MerkleIndex._tracked(t, v):
                    assert truth.get(k, (None, None))[1] == sigs.canonical(v)

    run(go())


# ------------------------------------------------- crash-safe snapshots (v2)


def _node(name="r0", quorum=1):
    return BFTABDNode(name, [name, "r1"], "sup", InMemoryNet(),
                      ReplicaConfig(quorum_size=quorum))


def test_snapshot_v2_roundtrip_preserves_inflight_nonces(tmp_path):
    """Satellite: the FULL anti-replay map survives the round trip — an
    in-flight (unexpired) nonce must not become replayable after restore."""
    from dds_tpu.core import snapshot as snap

    node = _node()
    node._store("k", M.ABDTag(2, "r0"), [1, 2])
    node.incoming[111] = False   # in-flight
    node.incoming[222] = True    # expired
    snap.save_replica(node, tmp_path)
    fresh = _node()
    assert snap.load_replica(fresh, tmp_path)
    assert fresh.incoming[111] is False
    assert fresh.incoming[222] is True
    assert fresh.repository["k"] == (M.ABDTag(2, "r0"), [1, 2])
    assert fresh.merkle.root() == node.merkle.root()  # index rebuilt on load
    assert fresh.snapshot_meta["generation"] == 1


def test_snapshot_bitflip_quarantined_falls_back_to_older_generation(tmp_path):
    """Acceptance: one flipped byte -> the file is quarantined (renamed
    aside), never loaded; the next-older verified generation restores."""
    from dds_tpu.core import snapshot as snap

    node = _node()
    node._store("k1", M.ABDTag(1, "r0"), ["gen1"])
    snap.save_replica(node, tmp_path)
    node._store("k1", M.ABDTag(2, "r0"), ["gen2"])
    p2 = snap.save_replica(node, tmp_path)
    raw = bytearray(p2.read_bytes())
    raw[len(raw) // 2] ^= 0x01
    p2.write_bytes(bytes(raw))
    fresh = _node()
    assert snap.load_replica(fresh, tmp_path)
    assert fresh.repository["k1"][1] == ["gen1"]  # older generation won
    corrupt = list(tmp_path.glob("*.corrupt"))
    assert len(corrupt) == 1 and "00000002" in corrupt[0].name
    assert not any("00000002" in p.name for p in tmp_path.glob("*.json"))


def test_snapshot_forged_footer_rejected(tmp_path):
    from dds_tpu.core import snapshot as snap

    node = _node()
    node._store("k", M.ABDTag(1, "r0"), ["secret-keyed"])
    snap.save_replica(node, tmp_path, secret=b"key-A")
    fresh = _node()
    # an attacker without the snapshot key cannot plant a loadable file
    assert not snap.load_replica(fresh, tmp_path, secret=b"key-B")
    assert not fresh.repository
    assert list(tmp_path.glob("*.corrupt"))


def test_snapshot_rotation_keeps_n_generations(tmp_path):
    from dds_tpu.core import snapshot as snap

    node = _node()
    for i in range(6):
        node._store("k", M.ABDTag(i + 1, "r0"), [i])
        snap.save_replica(node, tmp_path, keep=2)
    gens = sorted(p.name for p in tmp_path.glob("*.json"))
    assert gens == ["r0.snapshot.00000005.json", "r0.snapshot.00000006.json"]


def test_corrupt_legacy_snapshot_quarantined_not_crashing(tmp_path):
    """Satellite: corrupt/truncated v1 JSON is treated as missing — warned
    and quarantined as `<name>.snapshot.corrupt`, never raised."""
    from dds_tpu.core import snapshot as snap

    (tmp_path / "r0.snapshot.json").write_text('{"repository": {truncated')
    fresh = _node()
    assert not snap.load_replica(fresh, tmp_path)
    assert (tmp_path / "r0.snapshot.corrupt").exists()
    assert not (tmp_path / "r0.snapshot.json").exists()


def test_corrupt_snapshots_do_not_abort_launch(tmp_path):
    """Acceptance at BOOT: run.launch with a snapshot dir full of corrupt
    files (flipped v2 + garbage v1) boots cleanly and quarantines both."""

    async def go():
        from dds_tpu.core import snapshot as snap
        from dds_tpu.run import launch
        from dds_tpu.utils.config import DDSConfig

        cfg = DDSConfig()
        cfg.proxy.port = 0
        cfg.recovery.enabled = False
        cfg.recovery.snapshot_dir = str(tmp_path)
        cfg.recovery.anti_entropy_enabled = False

        # a valid v2 file for replica-0, then flip one byte
        node = BFTABDNode("replica-0", ["replica-0"], "sup", InMemoryNet(),
                          ReplicaConfig())
        node._store("k", M.ABDTag(3, "replica-0"), ["payload"])
        secret = snap.derive_secret(cfg.security.abd_mac_secret.encode())
        p = snap.save_replica(node, tmp_path, secret=secret)
        raw = bytearray(p.read_bytes())
        raw[10] ^= 0x01
        p.write_bytes(bytes(raw))
        # garbage v1 for replica-1
        (tmp_path / "replica-1.snapshot.json").write_text("not json at all")

        dep = await launch(cfg)
        try:
            r0 = dep.replicas["replica-0"]
            assert "k" not in r0.repository          # forged file NOT loaded
            assert list(tmp_path.glob("*.corrupt"))  # both quarantined
            assert (tmp_path / "replica-1.snapshot.corrupt").exists()
        finally:
            await dep.stop()

    run(go())


# ------------------------------------------------------ observability surface


def test_health_and_metrics_expose_recovery_gauges(tmp_path):
    """Satellite: /health grows an Aegis recovery section and /metrics the
    anti-entropy + snapshot gauge families."""

    async def go():
        from dds_tpu.core import snapshot as snap
        from dds_tpu.http.miniserver import http_request
        from dds_tpu.run import launch
        from dds_tpu.utils.config import DDSConfig
        import json as _json

        cfg = DDSConfig()
        cfg.proxy.port = 0
        cfg.recovery.enabled = False
        cfg.recovery.snapshot_dir = str(tmp_path)
        cfg.recovery.anti_entropy_interval = 30.0  # loop exists, won't fire
        dep = await launch(cfg)
        try:
            secret = snap.derive_secret(cfg.security.abd_mac_secret.encode())
            snap.save_all(dep.replicas, tmp_path, secret=secret)
            # one sync round so last_sync_age is populated
            node = dep.replicas["replica-0"]
            await node.antientropy.sync_once("replica-1")
            host, port = cfg.proxy.host, dep.server.cfg.port
            status, body = await http_request(host, port, "GET", "/health")
            assert status == 200
            health = _json.loads(body)
            rec = health["recovery"]
            assert rec["replica-0"]["anti_entropy"]["rounds"] >= 1
            assert rec["replica-0"]["anti_entropy"]["last_sync_age"] is not None
            assert rec["replica-0"]["anti_entropy"]["running"] is True
            assert rec["replica-0"]["snapshot"]["generation"] == 1
            assert rec["replica-0"]["snapshot"]["age"] is not None
            # the counter is process-global (other tests may have bumped
            # it); here it only needs to be present and numeric
            assert rec["replica-0"]["snapshot"]["verify_failures"] >= 0
            status, body = await http_request(host, port, "GET", "/metrics")
            assert status == 200
            text = body.decode()
            assert "dds_antientropy_divergent_buckets" in text
            assert "dds_antientropy_last_sync_age_seconds" in text
            assert "dds_snapshot_generation" in text
            assert "dds_snapshot_age_seconds" in text
        finally:
            await dep.stop()

    run(go())
