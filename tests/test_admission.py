"""Bulwark overload-control tests (ISSUE 7).

The admission math — token-bucket refill/burst, priority ordering,
shed/unshed hysteresis, adaptive coalescing — runs on FAKE clocks, so
every ratchet step is deterministic. The storage-layer fast-fail and the
REST surface (429/503 with derived Retry-After, exempt observability
routes) run on small real stacks. The flagship drives a seeded ChaosNet
flood twice — admission off, then on — and asserts the acceptance claim:
Bulwark-enabled interactive goodput beats the no-admission baseline,
shed requests complete in a fraction of the Deadline budget, transitions
are flight-recorded with dds_admission_* metrics, and /health + /slo
stay reachable throughout.
"""

import asyncio
import contextlib
import json
import random
import time

import pytest

from dds_tpu.core.admission import (
    CLASSES,
    AdaptiveCoalescer,
    AdmissionController,
    TokenBucket,
    route_class,
)
from dds_tpu.core.errors import AllBreakersOpenError
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.http.miniserver import http_request, http_request_full
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.obs.metrics import metrics
from dds_tpu.utils.config import AdmissionConfig, DDSConfig
from dds_tpu.utils.retry import Deadline

pytestmark = pytest.mark.overload


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------ token-bucket math


def test_token_bucket_burst_refill_and_eta():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=4.0, clock=clk)
    # the full burst is available up front, then the bucket is dry
    assert all(b.try_acquire() for _ in range(4))
    assert not b.try_acquire()
    # refill is linear in elapsed time: 0.5 s -> 1 token
    assert b.refill_eta() == pytest.approx(0.5)
    clk.advance(0.5)
    assert b.try_acquire()
    assert not b.try_acquire()
    # capacity clamps: a long idle period never exceeds the burst
    clk.advance(3600.0)
    assert b.tokens == pytest.approx(4.0)
    for _ in range(4):
        b.try_acquire()
    # eta for a multi-token ask scales with the deficit
    assert b.refill_eta(3.0) == pytest.approx(1.5)


def test_token_bucket_zero_rate_never_refills():
    clk = FakeClock()
    b = TokenBucket(rate=0.0, burst=1.0, clock=clk)
    assert b.try_acquire()
    clk.advance(1e6)
    assert not b.try_acquire()
    assert b.refill_eta() == float("inf")


def test_route_priority_classes_and_overrides():
    assert CLASSES[route_class("GetSet")] == "interactive"
    assert CLASSES[route_class("PutSet")] == "interactive"
    assert CLASSES[route_class("SumAll")] == "aggregate"
    assert CLASSES[route_class("MatVec")] == "aggregate"
    assert CLASSES[route_class("_sync")] == "background"
    assert CLASSES[route_class("NoSuchRoute")] == "background"
    # operator overrides win; junk override values are ignored
    assert CLASSES[route_class("SearchEq", {"SearchEq": "background"})] \
        == "background"
    assert CLASSES[route_class("SumAll", {"SumAll": "bogus"})] == "aggregate"


# ------------------------------------------------- shed ratchet/hysteresis


def _controller(clk, alerts=None, breakers=None, **kw):
    state = {"alerts": alerts or set(), "breakers": breakers or (0, [])}
    kw.setdefault("rates", {})  # unthrottled: these tests isolate shedding
    c = AdmissionController(
        eval_interval=1.0,
        shed_hold=3,
        max_shed_level=kw.pop("max_shed_level", 3),
        alerts=lambda: state["alerts"],
        breakers=lambda: state["breakers"],
        clock=clk,
        **kw,
    )
    return c, state


def test_shed_ratchet_sheds_lowest_class_first():
    clk = FakeClock()
    c, state = _controller(clk)
    assert c.decide("_sync").admitted  # healthy: everything flows
    state["alerts"] = {"GetSet"}  # interactive burning budget = distress
    for expected in (1, 2, 3):
        clk.advance(1.0)
        assert c.evaluate() == expected
    clk.advance(1.0)
    assert c.evaluate() == 3  # clamped at max_shed_level

    # priority ordering at each level, checked via fresh controllers
    for level, admitted in ((1, {"GetSet": True, "SumAll": True, "_sync": False}),
                            (2, {"GetSet": True, "SumAll": False, "_sync": False}),
                            (3, {"GetSet": False, "SumAll": False, "_sync": False})):
        c2, s2 = _controller(FakeClock())
        c2.shed_level = level
        for route, want in admitted.items():
            d = c2.decide(route)
            assert d.admitted == want, (level, route)
            if not want:
                assert d.status == 503


def test_unshed_hysteresis_steps_down_one_level_per_hold():
    clk = FakeClock()
    c, state = _controller(clk)
    state["alerts"] = {"SumAll"}
    clk.advance(1.0)
    assert c.evaluate() == 1
    clk.advance(1.0)
    assert c.evaluate() == 2
    # recovery: alert clears, but un-shedding needs shed_hold=3 clean
    # evaluations per level — and any distress resets the streak
    state["alerts"] = set()
    clk.advance(1.0)
    assert c.evaluate() == 2
    clk.advance(1.0)
    assert c.evaluate() == 2
    state["alerts"] = {"GetSet"}  # relapse mid-recovery
    clk.advance(1.0)
    assert c.evaluate() == 3  # distress ratchets straight back up
    state["alerts"] = set()
    for _ in range(2):
        clk.advance(1.0)
        assert c.evaluate() == 3
    clk.advance(1.0)
    assert c.evaluate() == 2  # third clean eval: one level down
    for _ in range(6):  # two more holds of 3 walk 2 -> 1 -> 0
        clk.advance(1.0)
        c.evaluate()
    assert c.shed_level == 0  # and eventually all the way down


def test_shed_class_burn_does_not_latch_the_ratchet():
    """A shed class 503s by construction; its own burn alert must not
    count as distress or the ratchet could never recover."""
    clk = FakeClock()
    c, state = _controller(clk)
    state["alerts"] = {"_sync"}  # background burning
    clk.advance(1.0)
    assert c.evaluate() == 1  # background now shed
    # the background alert keeps firing (shed 503s burn its budget), but
    # it is no longer a SERVED class: clean evals walk the level back down
    for _ in range(3):
        clk.advance(1.0)
        c.evaluate()
    assert c.shed_level == 0


def test_breaker_census_triggers_shed_and_retry_after():
    clk = FakeClock()
    c, state = _controller(clk)
    state["breakers"] = (4, [3.2, 5.0])  # 2 of 4 refusing = fraction 0.5
    clk.advance(1.0)
    assert c.evaluate() == 1
    d = c.decide("_sync")
    assert not d.admitted and d.status == 503
    # shed Retry-After prefers the nearest breaker half-open probe
    assert d.retry_after == pytest.approx(3.2)
    # without breaker ETAs it falls back to the ratchet cadence
    state["breakers"] = (4, [])
    state["alerts"] = {"GetSet"}
    d = c.decide("_sync")
    assert d.retry_after == pytest.approx(c.eval_interval * c.shed_hold)


def test_tenant_token_buckets_isolate_the_hot_tenant():
    clk = FakeClock()
    c = AdmissionController(
        rates={"interactive": (1.0, 2.0)}, clock=clk,
        eval_interval=1e9,  # no ratchet in this test
    )
    assert c.decide("GetSet", tenant="hot").admitted
    assert c.decide("GetSet", tenant="hot").admitted
    d = c.decide("GetSet", tenant="hot")
    assert not d.admitted and d.status == 429
    assert d.retry_after == pytest.approx(1.0)  # 1 token at 1/s
    # a different tenant has its own bucket: unaffected
    assert c.decide("GetSet", tenant="cold").admitted
    # ...and the hot tenant recovers by waiting out the eta
    clk.advance(1.0)
    assert c.decide("GetSet", tenant="hot").admitted


def test_transitions_are_metered_and_flight_recorded(tmp_path):
    from dds_tpu.obs.flight import flight

    clk = FakeClock()
    flight.configure(dir=str(tmp_path), min_interval=0.0)
    try:
        c, state = _controller(clk)
        state["alerts"] = {"GetSet"}
        clk.advance(1.0)
        c.evaluate()
        state["alerts"] = set()
        for _ in range(3):
            clk.advance(1.0)
            c.evaluate()
        assert c.shed_level == 0
        assert [t["direction"] for t in c.transitions] == ["shed", "unshed"]
        assert (metrics.value("dds_admission_transitions_total",
                              direction="shed", reason="slo_burn") or 0) >= 1
        assert (metrics.value("dds_admission_transitions_total",
                              direction="unshed", reason="recovered") or 0) >= 1
        index = (tmp_path / "index.jsonl").read_text().splitlines()
        kinds = [json.loads(line)["kind"] for line in index]
        assert "admission_shed" in kinds and "admission_unshed" in kinds
    finally:
        flight.configure(dir="")


# ------------------------------------------------- storage-layer fast-fail


def _open_all_breakers(abd: AbdClient, reset: float):
    from dds_tpu.utils.retry import CircuitBreaker

    for n in abd.replicas.get_trusted():
        b = abd.breakers[n] = CircuitBreaker(3, reset, name=n)
        for _ in range(3):
            b.record_failure()
        assert not b.allow()


def test_fast_fail_when_no_probe_fits_the_budget():
    """All trusted coordinators' breakers open, nearest half-open probe
    beyond the remaining budget: the op must degrade in microseconds with
    the typed error instead of burning the Deadline on futile attempts."""

    async def go():
        net = InMemoryNet()
        abd = AbdClient("proxy-ff", net, ["r0", "r1"],
                        AbdClientConfig(request_timeout=5.0, quorum_size=2))
        _open_all_breakers(abd, reset=60.0)
        dl = Deadline(0.5)
        t0 = time.perf_counter()
        with pytest.raises(AllBreakersOpenError) as ei:
            await abd.fetch_set("k", deadline=dl)
        assert time.perf_counter() - t0 < 0.1  # no timeout was burned
        assert ei.value.eta > dl.remaining()
        assert ei.value.targets == 2
        # the batched tag round fast-fails identically
        with pytest.raises(AllBreakersOpenError):
            await abd.read_tags(["k"], deadline=dl)
        assert (metrics.value("dds_fast_fail_total", op="fetch") or 0) >= 1

    asyncio.run(go())


def test_no_fast_fail_while_a_probe_still_fits():
    """With the half-open probe inside the budget, the degraded try must
    proceed (it is what heals the breaker) — here it times out against
    unregistered endpoints instead of failing instantly."""

    async def go():
        net = InMemoryNet()
        abd = AbdClient("proxy-ff2", net, ["r0", "r1"],
                        AbdClientConfig(request_timeout=0.05))
        _open_all_breakers(abd, reset=0.2)
        with pytest.raises(asyncio.TimeoutError):
            await abd.fetch_set("k", deadline=Deadline(1.0))

    asyncio.run(go())


def test_fast_fail_disabled_by_config_flag():
    async def go():
        net = InMemoryNet()
        abd = AbdClient(
            "proxy-ff3", net, ["r0"],
            AbdClientConfig(request_timeout=0.05, fast_fail_all_open=False),
        )
        _open_all_breakers(abd, reset=60.0)
        with pytest.raises(asyncio.TimeoutError):
            await abd.fetch_set("k", deadline=Deadline(0.5))

    asyncio.run(go())


# ------------------------------------------------------------ REST surface


@contextlib.asynccontextmanager
async def admission_stack(acfg: AdmissionConfig | None = None, n=4, quorum=3):
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig

    net = InMemoryNet()
    rcfg = ReplicaConfig(quorum_size=quorum)
    addrs = [f"replica-{i}" for i in range(n)]
    replicas = {a: BFTABDNode(a, addrs, "supervisor", net, rcfg) for a in addrs}
    abd = AbdClient("proxy-0", net, addrs,
                    AbdClientConfig(request_timeout=2.0, quorum_size=quorum))
    server = DDSRestServer(
        abd, ProxyConfig(host="127.0.0.1", port=0, admission=acfg)
    )
    await server.start()
    try:
        yield server, replicas
    finally:
        await server.stop()


def test_throttle_answers_429_with_refill_retry_after():
    acfg = AdmissionConfig(enabled=True, aggregate_rate=0.5,
                           aggregate_burst=1.0, eval_interval=1e9)

    async def go():
        async with admission_stack(acfg) as (server, _):
            status, _ = await http_request(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": ["12345"]}).encode(),
            )
            assert status == 200
            status, _ = await http_request(
                "127.0.0.1", server.cfg.port, "GET",
                "/SumAll?position=0&nsqr=77",
            )
            assert status == 200  # burst of 1
            t0 = time.perf_counter()
            status, headers, _ = await http_request_full(
                "127.0.0.1", server.cfg.port, "GET",
                "/SumAll?position=0&nsqr=77",
            )
            assert status == 429
            assert time.perf_counter() - t0 < 0.2  # microseconds, not budget
            # Retry-After = ceil(refill eta) at 0.5 tokens/s = 2 s
            assert headers["retry-after"] == "2"
            assert (metrics.value("dds_admission_requests_total",
                                  outcome="throttled",
                                  **{"class": "aggregate"}) or 0) >= 1

    asyncio.run(go())


def test_tenant_header_separates_budgets_at_the_edge():
    acfg = AdmissionConfig(enabled=True, interactive_rate=0.1,
                           interactive_burst=1.0, eval_interval=1e9)

    async def go():
        async with admission_stack(acfg) as (server, _):
            async def get(tenant):
                r, w = await asyncio.open_connection("127.0.0.1",
                                                     server.cfg.port)
                w.write(
                    b"GET /GetSet/deadbeef HTTP/1.1\r\nHost: x\r\n"
                    b"x-dds-tenant: " + tenant.encode() + b"\r\n"
                    b"Content-Length: 0\r\nConnection: close\r\n\r\n"
                )
                await w.drain()
                status = int((await r.readline()).split()[1])
                w.close()
                return status

            assert await get("alice") == 404  # admitted (missing key)
            assert await get("alice") == 429  # alice's bucket is dry
            assert await get("bob") == 404    # bob's is not

    asyncio.run(go())


def test_observability_routes_answer_during_a_full_shed():
    """ISSUE 7 satellite: /health, /metrics, /slo (and /shards where
    sharded) are admission-exempt so the system stays debuggable while
    overloaded — a full shed must not silence them."""
    acfg = AdmissionConfig(enabled=True, max_shed_level=3, eval_interval=1e9)

    async def go():
        async with admission_stack(acfg) as (server, _):
            server.admission.shed_level = 3  # force a full shed
            status, headers, _ = await http_request_full(
                "127.0.0.1", server.cfg.port, "GET", "/GetSet/abc"
            )
            assert status == 503 and "retry-after" in headers
            status, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/health"
            )
            assert status in (200, 503) and json.loads(body)["status"]
            status, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/metrics"
            )
            assert status == 200
            assert "dds_admission_shed_level 3" in body.decode()
            status, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/slo"
            )
            assert status == 200
            report = json.loads(body)["admission"]
            assert report["shed_level"] == 3
            assert report["shedding"] == list(CLASSES)

    asyncio.run(go())


def test_degraded_retry_after_derived_from_breaker_eta():
    """ISSUE 7 satellite: the 503 paths derive Retry-After from the
    nearest breaker half-open ETA instead of the config constant, which
    remains only as the fallback."""

    async def go():
        async with admission_stack(None) as (server, _):
            assert server.admission is None  # admission off: still derived
            server.abd.breaker_census = lambda: (4, [3.2, 9.0])
            resp = server._unavailable("quorum down")
            assert resp.headers["Retry-After"] == "4"
            # an explicit candidate (fast-fail ETA) can be nearer still
            resp = server._unavailable("quorum down", eta=1.4)
            assert resp.headers["Retry-After"] == "2"
            # no measurable recovery pending -> the config hint
            server.abd.breaker_census = lambda: (4, [])
            resp = server._unavailable("quorum down")
            assert resp.headers["Retry-After"] == str(
                max(1, round(server.cfg.retry_after_hint))
            )

    asyncio.run(go())


# ------------------------------------------------------ adaptive coalescing


def test_adaptive_coalescer_fills_under_load_and_snaps_when_idle():
    clk = FakeClock()
    c = AdaptiveCoalescer(base_window=0.002, max_window=0.02,
                          target_folds=8.0, clock=clk)
    assert c.window() == pytest.approx(0.002)  # idle: base window
    # sustained 1 kHz fold arrivals -> rate ~1000/s -> window ~ 8/1000
    # (the EWMA time constant is half_life=1 s, so feed ~5 s of arrivals)
    for _ in range(5000):
        clk.advance(0.001)
        c.note_fold()
    assert c.rate() == pytest.approx(1000.0, rel=0.05)
    assert c.window() == pytest.approx(0.008, rel=0.05)
    # moderate load clamps at max_window (100/s -> 80 ms > 20 ms cap)
    c2 = AdaptiveCoalescer(0.002, 0.02, target_folds=8.0, clock=clk)
    for _ in range(200):
        clk.advance(0.01)
        c2.note_fold()
    assert c2.window() == pytest.approx(0.02)
    # going idle decays the estimate: the window snaps back to base
    clk.advance(30.0)
    assert c.window() == pytest.approx(0.002)
    assert c2.window() == pytest.approx(0.002)


def test_server_wires_adaptive_window():
    acfg = AdmissionConfig(enabled=True, adaptive_coalesce=True,
                           coalesce_max_window=0.05, eval_interval=1e9)

    async def go():
        async with admission_stack(acfg) as (server, _):
            assert server._coalescer is not None
            assert server._coalesce_window() == pytest.approx(
                server.cfg.coalesce_window
            )  # idle: the configured base
            assert server._coalescer.max_window == pytest.approx(0.05)
        async with admission_stack(None) as (server, _):
            assert server._coalescer is None
            assert server._coalesce_window() == server.cfg.coalesce_window

    asyncio.run(go())


# --------------------------------------------------- flagship: the cliff


def _overload_cfg(admission: bool, seed: int, budget: float,
                  flight_dir: str = "") -> DDSConfig:
    cfg = DDSConfig()
    cfg.replicas.endpoints = [f"replica-{i}" for i in range(4)]
    cfg.replicas.sentinent = []
    cfg.replicas.byz_quorum_size = 3
    cfg.replicas.byz_max_faults = 1
    cfg.proxy.port = 0
    cfg.proxy.request_budget = budget
    cfg.proxy.intranet_request_timeout = budget / 2
    cfg.recovery.enabled = False
    cfg.recovery.anti_entropy_enabled = False
    cfg.obs.audit_enabled = False
    cfg.obs.flight_dir = flight_dir
    cfg.obs.slo_fast_window = 1.0
    cfg.obs.slo_slow_window = 2.0
    cfg.attacks.enabled = True
    cfg.attacks.chaos_enabled = True
    cfg.attacks.chaos_seed = seed
    cfg.admission.enabled = admission
    cfg.admission.eval_interval = 0.1
    cfg.admission.shed_hold = 8
    # admit enough aggregates that the SLO engine SEES the overload (they
    # exhaust their budgets and burn), so the shed ratchet fires mid-run
    cfg.admission.aggregate_rate = 30.0
    cfg.admission.aggregate_burst = 30.0
    # an aggressive aggregate objective: admitted folds running past 20 ms
    # under overload burn the SumAll budget, so the multiwindow alert (and
    # with it the shed ratchet) fires organically mid-run
    cfg.obs.slo_routes = {"SumAll": {"objective": 0.99, "latency-ms": 20.0}}
    return cfg


async def _drive_overload(admission: bool, tmp_path) -> dict:
    """One seeded ChaosNet flood run; returns goodput + shed stats."""
    from dds_tpu.run import launch

    seed, budget, duration, bits, n_keys = 7, 1.0, 1.6, 4096, 160
    flight_dir = str(tmp_path / ("bulwark" if admission else "baseline"))
    dep = await launch(_overload_cfg(admission, seed, budget, flight_dir))
    host, port = "127.0.0.1", dep.server.cfg.port
    rng = random.Random(seed)
    modulus = (1 << bits) - 159
    keys = []
    for _ in range(n_keys):
        status, body = await http_request(
            host, port, "POST", "/PutSet",
            json.dumps(
                {"contents": [str(rng.getrandbits(bits) % modulus)]}
            ).encode(), timeout=10.0,
        )
        assert status == 200
        keys.append(body.decode())

    results: list[tuple[str, int, float, bool]] = []
    probes: list[tuple[str, int]] = []

    async def call(klass, method, target):
        t0 = time.perf_counter()
        try:
            status, data = await http_request(host, port, method, target,
                                              timeout=budget + 2.0)
        except (OSError, asyncio.TimeoutError, EOFError, ConnectionError):
            status, data = -1, b""
        # admission rejections (429 throttle / 503 shed) vs degraded 503s
        # that burned their budget first: the rejection body is explicit,
        # so the "fail in microseconds" claim is measured on exactly the
        # requests Bulwark rejected at the edge
        rejected = status == 429 or (
            status == 503 and data.startswith(b"admission rejected")
        )
        results.append((klass, status, time.perf_counter() - t0, rejected))

    async def probe():
        # the acceptance claim: observability stays reachable THROUGHOUT
        for route in ("/health", "/slo"):
            try:
                status, _ = await http_request(host, port, "GET", route,
                                               timeout=2.0)
            except (OSError, asyncio.TimeoutError, EOFError, ConnectionError):
                status = -1
            probes.append((route, status))

    # Event-driven run length (the PR 5 delay-storm treatment): the old
    # fixed 1.6 s duration raced the shed ratchet against CI load — on a
    # slow machine the SLO burn windows could still be filling when the
    # drive stopped, and the "ratchet actually fired" assertion flaked.
    # Subscribing to the controller's transition hook makes the signal
    # explicit: the Bulwark run keeps driving (same open-loop schedule)
    # until the shed transition has BEEN OBSERVED, up to a hard cap, then
    # finishes the measurement window. The baseline run has no ratchet
    # and keeps the original duration.
    shed_seen = asyncio.Event()
    if admission:
        dep.server.admission.subscribe(
            lambda rec: shed_seen.set() if rec["direction"] == "shed" else None
        )
    max_duration = duration * 4

    dep.trudy.trigger("delay")
    sched = random.Random(seed + 1)
    tasks, t0, t = [], time.perf_counter(), 0.0
    flood_at, probe_at = 0.0, 0.0
    while t < duration or (
        admission and not shed_seen.is_set() and t < max_duration
    ):
        now = time.perf_counter() - t0
        if now < t:
            await asyncio.sleep(t - now)
        if t >= flood_at:
            dep.trudy.trigger("flood")
            flood_at += 0.3
        if t >= probe_at:
            tasks.append(asyncio.ensure_future(probe()))
            probe_at += 0.4
        # ~12 interactive + ~220 aggregate arrivals per second (open loop)
        key = keys[sched.randrange(len(keys))]
        tasks.append(asyncio.ensure_future(
            call("interactive", "GET", f"/GetSet/{key}")))
        for _ in range(18):
            tasks.append(asyncio.ensure_future(
                call("aggregate", "GET", f"/SumAll?position=0&nsqr={modulus}")))
        t += 0.08
    await asyncio.wait_for(asyncio.gather(*tasks), budget + 30.0)
    wall = time.perf_counter() - t0
    transitions = list(dep.server.admission.transitions) if admission else []
    await dep.stop()

    good = sum(1 for k, s, lat, _ in results
               if k == "interactive" and s == 200 and lat <= 0.3)
    shed_lat = sorted(lat for _, _, lat, rejected in results if rejected)
    return {
        "goodput": good / wall,
        "interactive": sum(1 for k, *_ in results if k == "interactive"),
        "shed": len(shed_lat),
        "shed_p50": shed_lat[len(shed_lat) // 2] if shed_lat else 0.0,
        "shed_p95": shed_lat[int(0.95 * len(shed_lat))] if shed_lat else 0.0,
        "probes": probes,
        "transitions": transitions,
        "flight_dir": flight_dir,
        "budget": budget,
    }


def test_overload_goodput_bulwark_beats_the_503_cliff(tmp_path):
    """Acceptance (ISSUE 7): under a seeded ChaosNet flood/overload
    schedule, Bulwark-enabled interactive goodput beats the no-admission
    baseline; shed requests complete in a small fraction of the Deadline
    budget; shed transitions are flight-recorded with dds_admission_*
    metrics; /health and /slo answer throughout."""
    import pathlib

    from dds_tpu.obs.flight import flight

    try:
        baseline = asyncio.run(_drive_overload(False, tmp_path))
        bulwark = asyncio.run(_drive_overload(True, tmp_path))
    finally:
        flight.configure(dir="")  # launch() armed the global recorder

    # the cliff: the same schedule that starves baseline interactive
    # traffic leaves Bulwark's interactive class serving
    assert bulwark["goodput"] > baseline["goodput"] * 1.5, (baseline, bulwark)
    assert bulwark["goodput"] > 3.0, bulwark

    # shed requests fail fast instead of burning the Deadline like the
    # baseline's 503s do: typically ~1 ms server-side — the p50 bound is
    # an order of magnitude under the budget, and even the client-observed
    # tail (which rides the congested pre-shed event loop) stays under
    # half of it
    assert bulwark["shed"] > 50
    assert bulwark["shed_p50"] < bulwark["budget"] / 10, bulwark["shed_p50"]
    assert bulwark["shed_p95"] < bulwark["budget"] / 2, bulwark["shed_p95"]

    # the ratchet actually fired (admitted aggregates burned the SumAll
    # budget -> multiwindow alert -> shed), was metered and flight-recorded
    assert any(t["direction"] == "shed" for t in bulwark["transitions"])
    assert (metrics.value("dds_admission_transitions_total",
                          direction="shed", reason="slo_burn") or 0) >= 1
    index = pathlib.Path(bulwark["flight_dir"]) / "index.jsonl"
    kinds = [json.loads(line)["kind"]
             for line in index.read_text().splitlines()]
    assert "admission_shed" in kinds

    # observability stayed reachable through the whole flood (the claim
    # is about the Bulwark run — the baseline's jammed loop answering its
    # exempt probes slowly is exactly the cliff being demonstrated)
    assert bulwark["probes"], "no probes recorded"
    assert all(s in (200, 503) for _, s in bulwark["probes"]), bulwark["probes"]
    assert all(s == 200 for r, s in bulwark["probes"] if r == "/slo")


# ------------------------------------------------------------------ sentry


def test_sentry_check_parses_overload_records(tmp_path):
    from benchmarks.sentry import _check_overload_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "overload goodput interactive",
        "value": 31.1, "unit": "req/s", "vs_baseline": 233.9,
        "detail": {
            "baseline_goodput": 0.133, "shed_requests": 1157,
            "shed_p95_ms": 8.7, "aggregate_rate": 400.0,
        },
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_overload_records(str(tmp_path)) == {"rows": 1}
    bad = dict(good, detail={"baseline_goodput": 0.1})  # missing shed census
    (bench / "results.json").write_text(json.dumps([good, bad]))
    with pytest.raises(ValueError):
        _check_overload_records(str(tmp_path))
    # other record families are ignored by this checker
    (bench / "results.json").write_text(
        json.dumps([{"metric": "analytics matvec: x", "value": -1}])
    )
    assert _check_overload_records(str(tmp_path)) == {"rows": 0}
