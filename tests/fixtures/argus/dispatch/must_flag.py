"""Must-flag corpus for the ``dispatch`` pass: every rule fires.

Never imported — linted as text by tests/test_argus.py.
"""

import jax
import numpy as np


def retrace_bomb(xs, m):
    fn = jax.jit(lambda v: v % m)          # dispatch.jit-per-call
    return fn(xs)


def per_iteration_sync(chunks):
    total = 0
    for c in chunks:
        total += c.sum().item()            # dispatch.host-roundtrip
        host = np.asarray(c)               # dispatch.host-roundtrip
        total += int(host[0])
    return total


def stray_wait(y):
    y.block_until_ready()                  # dispatch.stray-sync
    return y
