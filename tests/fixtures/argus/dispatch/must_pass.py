"""Must-pass twin of the ``dispatch`` corpus: every caching discipline
the repo uses, plus the hoisted-transfer form of the hot loop."""

import functools
import threading

import jax
import numpy as np

_FN_CACHE = {}
_FN_LOCK = threading.Lock()


def cached_fn(m):
    with _FN_LOCK:
        fn = _FN_CACHE.get(m)
        if fn is None:
            fn = jax.jit(lambda v: v % m)
            _FN_CACHE[m] = fn
    return fn


@functools.lru_cache(maxsize=8)
def cached_builder(m):
    return jax.jit(lambda v: v % m)


class Plan:
    def __init__(self, m):
        self._fn = jax.jit(lambda v: v % m)

    @functools.cached_property
    def doubler(self):
        return jax.jit(lambda v: v * 2)


_FN_CACHE_MAX = 64


def _fn_cache_put(key, fn):
    # ops/predicate's eviction discipline: FIFO-capped insert under the
    # lock, tuple keys per op family (shapes retrace under one entry)
    with _FN_LOCK:
        while len(_FN_CACHE) >= _FN_CACHE_MAX:
            _FN_CACHE.pop(next(iter(_FN_CACHE)), None)
        _FN_CACHE[key] = fn


def predicate_mask(op, values):
    # ops/predicate's dispatch shape: tuple-keyed lookup, jit on miss,
    # helper-mediated insert — the `*fn_cache*` helper IS the discipline
    key = ("cmp", op)
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = jax.jit(lambda v: v % 2 == 0 if op == "even" else v % 2 == 1)
        _fn_cache_put(key, fn)
    return fn(values)


def hoisted_transfer(chunks):
    stacked = np.asarray(chunks)            # one transfer, outside the loop
    total = 0
    for row in stacked:
        total += int(row[0])
    return total
