"""Must-pass twin of the ``dispatch`` corpus: every caching discipline
the repo uses, plus the hoisted-transfer form of the hot loop."""

import functools
import threading

import jax
import numpy as np

_FN_CACHE = {}
_FN_LOCK = threading.Lock()


def cached_fn(m):
    with _FN_LOCK:
        fn = _FN_CACHE.get(m)
        if fn is None:
            fn = jax.jit(lambda v: v % m)
            _FN_CACHE[m] = fn
    return fn


@functools.lru_cache(maxsize=8)
def cached_builder(m):
    return jax.jit(lambda v: v % m)


class Plan:
    def __init__(self, m):
        self._fn = jax.jit(lambda v: v % m)

    @functools.cached_property
    def doubler(self):
        return jax.jit(lambda v: v * 2)


def hoisted_transfer(chunks):
    stacked = np.asarray(chunks)            # one transfer, outside the loop
    total = 0
    for row in stacked:
        total += int(row[0])
    return total
