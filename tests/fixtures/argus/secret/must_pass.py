"""Must-pass twin of the ``secret`` corpus: the same decrypt, lifetime-
clean — builtin ``pow`` for host math and the consts-passing
``powmod_batch_with_consts`` twin for device batches (no module-wide
memoization keyed on secret-derived moduli)."""


def decrypt_batch_host(key, cs):
    n2 = key.p * key.q
    lam = key.lam
    return [pow(c, lam, n2) for c in cs]


def decrypt_batch_device(key, backend, cs, consts):
    n2 = key.p * key.q
    return backend.powmod_batch_with_consts(cs, key.lam, n2, consts)


def tenant_rotate_reencrypt(old, new, cs):
    """Bastion keyring rotation: the retiring epoch's decrypt and the
    incoming epoch's encrypt both stay on lifetime-clean paths —
    builtin ``pow`` end to end, so no module-wide cache ever retains a
    tenant's modulus past its epoch."""
    n2_old = old.p * old.q
    plains = [pow(c, old.lam, n2_old) for c in cs]
    n2_new = new.p * new.q
    return [pow(1 + m * new.n, 1, n2_new) for m in plains]


def tenant_shred(keyring, tenant):
    """Crypto-shredding: zero-fill every key in the tenant's family.
    Secret attributes are only ever STORED to here, never read — the
    deletion path has no value flow for the taint engine to chase."""
    for key in keyring.family(tenant):
        key.p = key.q = key.lam = 0
