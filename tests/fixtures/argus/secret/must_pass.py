"""Must-pass twin of the ``secret`` corpus: the same decrypt, lifetime-
clean — builtin ``pow`` for host math and the consts-passing
``powmod_batch_with_consts`` twin for device batches (no module-wide
memoization keyed on secret-derived moduli)."""


def decrypt_batch_host(key, cs):
    n2 = key.p * key.q
    lam = key.lam
    return [pow(c, lam, n2) for c in cs]


def decrypt_batch_device(key, backend, cs, consts):
    n2 = key.p * key.q
    return backend.powmod_batch_with_consts(cs, key.lam, n2, consts)
