"""Must-flag corpus for the ``secret`` pass: secret-derived values reach
every sink class (context caches, lru builders, jit args, cached public
modexp entries).

Never imported — linted as text by tests/test_argus.py and kept in sync
with the ORIGINAL_PATTERN fixture in tests/test_sanctum.py.
"""

import functools

import jax

from dds_tpu.models.modmath import ModCtx
from dds_tpu.native import powmod


@functools.lru_cache(maxsize=None)
def cached_builder(n):
    return n * n


def decrypt_batch(key, backend, cs):
    n2 = key.p * key.q                     # taint seed: .p / .q
    ctx = ModCtx.make(n2)                  # secret-flow: ModCtx.make
    fn = jax.jit(lambda c: c % n2, n2)     # secret-flow: jax.jit arg
    cached_builder(key.lam)                # secret-flow: lru_cache builder
    ms = backend.powmod_batch(cs, key.lam, n2)   # secret-flow: powmod_batch
    return [powmod(c, key.lam, n2) for c in ms], ctx, fn
