"""Must-pass twin of the ``metrics`` corpus: the same series, bounded.

Help text is written once at the registration touch, wire-supplied
identifiers are bounded through an explicit capping call before they
become label values, and enum-like labels use literals.
"""

from dds_tpu.obs.metrics import metrics

_KNOWN_TENANTS = ("alpha", "beta")


def _cap(value: str, known=_KNOWN_TENANTS) -> str:
    return value if value in known else "other"


def registers_documented(n: int):
    metrics.set("dds_fixture_depth", n,
                help="fixture queue depth (bounded: no labels)")


def serve_request(tenant: str, seconds: float):
    metrics.inc("dds_fixture_requests_total",
                tenant=_cap(tenant),
                help="requests by tenant (capped to the known set)")
    metrics.observe("dds_fixture_seconds", seconds,
                    route="putset",
                    help="latency by route (literal label)")
    metrics.set("dds_fixture_last_seen", 1.0,
                shard="group-0",
                help="literal shard label")
