"""Must-flag corpus for the ``metrics`` pass: every rule fires.

Never imported — linted as text by tests/test_argus.py. Each flagged
line names its expected rule; the twin ``must_pass.py`` does the same
work the sanctioned way.
"""

from dds_tpu.obs.metrics import metrics


def registers_blank_help(n: int):
    metrics.set("dds_fixture_depth", n, help="")      # metrics.empty-help


def serve_request(tenant: str, key: str, trace_id: str, seconds: float):
    metrics.inc("dds_fixture_requests_total",          # metrics.unbounded-label
                tenant=tenant,
                help="requests by tenant")
    metrics.observe("dds_fixture_seconds", seconds,    # metrics.unbounded-label
                    key=key,
                    help="latency by key")
    metrics.set("dds_fixture_last_seen", 1.0,          # metrics.unbounded-label
                shard=f"group-{key}",
                help="interpolated shard label")
    metrics.inc("dds_fixture_failures_total",          # metrics.unbounded-label
                trace_id=trace_id,
                help="failures by exemplar trace")
