"""Must-flag corpus for the ``async`` pass: every rule fires.

Never imported — linted as text by tests/test_argus.py. Each flagged
line names its expected rule; the twin ``must_pass.py`` does the same
work the sanctioned way.
"""

import asyncio
import os
import subprocess
import threading
import time

from dds_tpu.obs.flight import flight

_LOCK = threading.Lock()


async def helper():
    await asyncio.sleep(0)


async def blocks_the_loop():
    time.sleep(0.1)                        # async.blocking-call
    subprocess.run(["true"])               # async.blocking-call
    data = open("/tmp/argus-fixture").read()   # async.blocking-call
    os.fsync(4)                            # async.blocking-call
    flight.record("incident", detail=data)     # async.blocking-call
    return data


async def drops_handles():
    asyncio.ensure_future(helper())        # async.dropped-task + bare-task-spawn
    helper()                               # async.unawaited-coroutine


async def holds_lock_across_await():
    with _LOCK:                            # async.lock-across-await
        await asyncio.sleep(0.1)
