"""Must-pass twin of the ``async`` corpus: the same work, sanctioned.

Blocking work hops to a worker thread, incidents use the async recorder,
spawns go through ``supervised_task`` (handle retained, crashes
reported), and the lock held across ``await`` is an ``asyncio.Lock``.
"""

import asyncio

from dds_tpu.obs.flight import flight
from dds_tpu.utils.tasks import supervised_task

_LOCK = asyncio.Lock()


def read_fixture() -> str:
    with open("/tmp/argus-fixture") as f:   # sync scope: fine
        return f.read()


def append_segment(payload: bytes) -> None:
    """Stratum-style durable append, sanctioned shape: the open + flush
    + fsync sequence lives in a SYNC function that async callers reach
    only through ``asyncio.to_thread`` — the fsync-before-rename
    discipline never runs on the event loop."""
    import os

    with open("/tmp/argus-fixture.tmp", "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace("/tmp/argus-fixture.tmp", "/tmp/argus-fixture.seg")


async def durable_append(payload: bytes) -> None:
    await asyncio.to_thread(append_segment, payload)


async def helper():
    await asyncio.sleep(0)


async def yields_to_the_loop():
    await asyncio.sleep(0.1)
    data = await asyncio.to_thread(read_fixture)
    await flight.record_async("incident", detail=data)
    return data


async def keeps_handles():
    task = supervised_task(helper(), name="fixture.helper")
    await task
    await helper()


async def holds_async_lock():
    async with _LOCK:
        await asyncio.sleep(0.1)


_STOP = asyncio.Event()


async def steer_loop():
    """Helmsman-style periodic controller tick, sanctioned shape: the
    loop is spawned supervised, each action is recorded through the
    async flight recorder, and shared decision state sits behind an
    ``asyncio.Lock``."""
    while not _STOP.is_set():
        async with _LOCK:
            await flight.record_async("helmsman", action="tick")
        await asyncio.sleep(0.1)


def start_steering():
    task = supervised_task(steer_loop(), name="fixture.steer")
    return task


def capture_exemplar(res: dict):
    """Chronoscope-style slow-trace exemplar capture, sanctioned shape:
    called from a tracer subscriber that may run ON the event-loop
    thread, so the blocking flight write is dispatched as a supervised
    task through the async recorder; only off-loop callers would write
    synchronously."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        flight.record("slow_trace", trace_id=res["trace_id"])
        return None
    return supervised_task(
        flight.record_async("slow_trace", trace_id=res["trace_id"]),
        name="fixture.exemplar",
    )


async def lease_keeper_loop(client):
    """Atlas-style read-local lease session keeper, sanctioned shape:
    the renewal loop is spawned supervised, the session state it mutates
    sits behind an ``asyncio.Lock``, and a lost lease is reported
    through the async flight recorder instead of a blocking call."""
    while not _STOP.is_set():
        async with _LOCK:
            lease = await client.ensure_lease()
        if lease is None:
            await flight.record_async("geo", action="lease_lost")
        await asyncio.sleep(0.1)


def start_lease_keeper(client):
    return supervised_task(lease_keeper_loop(client), name="fixture.lease")
