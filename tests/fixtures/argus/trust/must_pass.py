"""Must-pass twin of the ``trust`` corpus: the repo's actual handler
idiom — signature verification plus a nonce burn before any state
mutation (core/replica.py's shape)."""

import json

from dds_tpu.utils import sigs


class GuardedReplica:
    def __init__(self):
        self.repository = {}
        self.incoming = set()

    async def handle(self, sender, msg):
        req = json.loads(msg)
        if not sigs.validate_proxy_signature(sender, req):
            return
        if req["nonce"] in self.incoming:       # replay: already burned
            return
        self.incoming.add(req["nonce"])
        self.repository[req["key"]] = req["value"]


class GuardedProxy:
    def __init__(self):
        self.stored_keys = set()

    async def on_gossip(self, sender, payload):
        keys = json.loads(payload)
        if not sigs.verify_gossip_frame(sender, payload):
            return
        for k in keys:
            self.stored_keys.add(k)
