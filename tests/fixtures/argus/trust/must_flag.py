"""Must-flag corpus for the ``trust`` pass: wire input mutates state in
scopes with no verify/nonce guard at all.

Never imported — linted as text by tests/test_argus.py.
"""

import json


class NaiveReplica:
    def __init__(self):
        self.repository = {}
        self.incoming = set()

    async def handle(self, sender, msg):
        req = json.loads(msg)
        # trust.unverified-store: tainted key AND value, no guard in scope
        self.repository[req["key"]] = req["value"]


class NaiveProxy:
    def __init__(self):
        self.stored_keys = set()

    async def on_gossip(self, payload):
        keys = json.loads(payload)
        for k in keys:
            self.stored_keys.add(k)        # trust.unverified-store
