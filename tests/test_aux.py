"""Tests for auxiliary subsystems: tracing, snapshots, TLS (SURVEY §5)."""

import asyncio
import json
import ssl

import pytest

from dds_tpu.utils.trace import Tracer


# ------------------------------------------------------------------- tracing


def test_tracer_spans_and_summary():
    t = Tracer()
    for _ in range(3):
        with t.span("abd.fetch", key="k"):
            pass
    t.count("abd.suspect", 2)
    s = t.summary()
    assert s["abd.fetch"]["count"] == 3
    assert s["abd.fetch"]["p95_ms"] >= 0
    assert s["abd.suspect"]["count"] == 2
    assert len(t.events("abd.fetch")) == 3


def test_tracer_disabled_records_nothing():
    t = Tracer(enabled=False)
    with t.span("x"):
        pass
    t.count("y")
    assert t.summary() == {}


def test_tracer_dump_jsonl(tmp_path):
    t = Tracer()
    with t.span("a", foo=1):
        pass
    p = tmp_path / "trace.jsonl"
    assert t.dump_jsonl(str(p)) == 1
    rec = json.loads(p.read_text().strip())
    assert rec["name"] == "a" and rec["foo"] == 1


def test_tracer_bounded():
    t = Tracer(max_events=10)
    for i in range(25):
        t.record("e", 1.0)
    assert len(t.events()) == 10


# ----------------------------------------------------------------- snapshots


def test_snapshot_roundtrip(tmp_path):
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.messages import ABDTag
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    net = InMemoryNet()
    addrs = ["r0", "r1"]
    node = BFTABDNode("r0", addrs, "sup", net, ReplicaConfig(quorum_size=1))
    node.repository["k1"] = (ABDTag(3, "r0"), [1, "a", 2])
    node.repository["k2"] = (ABDTag(1, "r1"), None)
    node.incoming[12345] = True
    node.incoming[99] = False

    snap.save_replica(node, tmp_path)

    fresh = BFTABDNode("r0", addrs, "sup", InMemoryNet(), ReplicaConfig(quorum_size=1))
    assert snap.load_replica(fresh, tmp_path)
    assert fresh.repository["k1"] == (ABDTag(3, "r0"), [1, "a", 2])
    assert fresh.repository["k2"] == (ABDTag(1, "r1"), None)
    assert fresh.incoming[12345] is True
    assert 99 not in fresh.incoming  # only expired nonces persist


def test_snapshot_load_missing(tmp_path):
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    node = BFTABDNode("rX", ["rX"], "sup", InMemoryNet(), ReplicaConfig(quorum_size=1))
    assert not snap.load_replica(node, tmp_path)


def test_snapshot_save_all_load_all(tmp_path):
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.messages import ABDTag
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    net = InMemoryNet()
    addrs = ["r0", "r1", "r2"]
    replicas = {
        a: BFTABDNode(a, addrs, "sup", net, ReplicaConfig(quorum_size=2))
        for a in addrs
    }
    replicas["r1"].repository["k"] = (ABDTag(7, "r1"), ["x"])
    assert snap.save_all(replicas, tmp_path) == 3
    fresh = {
        a: BFTABDNode(a, addrs, "sup", InMemoryNet(), ReplicaConfig(quorum_size=2))
        for a in addrs
    }
    assert snap.load_all(fresh, tmp_path) == 3
    assert fresh["r1"].repository["k"] == (ABDTag(7, "r1"), ["x"])


# ----------------------------------------------------------------------- TLS


def test_tls_cert_generation_and_contexts(tmp_path):
    from dds_tpu.utils import tlsutil

    paths = tlsutil.generate_ca_and_cert(tmp_path, hosts=("127.0.0.1", "localhost"))
    for p in paths.values():
        assert p.exists()
    # idempotent
    again = tlsutil.generate_ca_and_cert(tmp_path)
    assert again == paths

    srv = tlsutil.server_context(paths["cert"], paths["key"], paths["ca"])
    assert srv.verify_mode == ssl.CERT_REQUIRED
    cli = tlsutil.client_context(paths["ca"], paths["cert"], paths["key"])
    assert cli.check_hostname is False


def test_mutual_tls_http_roundtrip(tmp_path):
    """Full mutual-TLS HTTP round trip through the miniserver."""
    from dds_tpu.http.miniserver import HttpServer, Response, http_request
    from dds_tpu.utils import tlsutil

    paths = tlsutil.generate_ca_and_cert(tmp_path)
    srv_ctx = tlsutil.server_context(paths["cert"], paths["key"], paths["ca"])
    cli_ctx = tlsutil.client_context(paths["ca"], paths["cert"], paths["key"])

    async def go():
        async def handler(req):
            return Response.text("secure-ok")

        server = HttpServer("127.0.0.1", 0, handler, srv_ctx)
        await server.start()
        try:
            status, body = await http_request(
                "127.0.0.1", server.port, "GET", "/", ssl_context=cli_ctx, timeout=5.0
            )
            assert status == 200 and body == b"secure-ok"
            # a client WITHOUT a cert is rejected by mutual auth
            anon = tlsutil.client_context(paths["ca"])
            with pytest.raises((ssl.SSLError, OSError, asyncio.TimeoutError)):
                await http_request(
                    "127.0.0.1", server.port, "GET", "/", ssl_context=anon, timeout=5.0
                )
        finally:
            await server.stop()

    asyncio.run(go())


def test_launch_with_tls_and_snapshots(tmp_path):
    """Boot the full deployment with TLS + snapshots enabled, run a client
    op over HTTPS, snapshot, and restore into a fresh boot."""
    import secrets

    from dds_tpu.core import snapshot as snap
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    async def go():
        cfg = DDSConfig()
        cfg.security.tls_enabled = True
        cfg.security.tls_dir = str(tmp_path / "certs")
        cfg.recovery.snapshot_dir = str(tmp_path / "snaps")
        cfg.recovery.enabled = False
        cfg.proxy.port = 0
        dep = await launch(cfg)
        try:
            body = json.dumps({"contents": [1, 2, 3]}).encode()
            status, key = await http_request(
                "127.0.0.1", dep.server.cfg.port, "POST", "/PutSet", body,
                ssl_context=dep.ssl_client, timeout=10.0,
            )
            assert status == 200
            snap.save_all(dep.replicas, cfg.recovery.snapshot_dir)
        finally:
            await dep.stop()

        # fresh boot restores the snapshots
        dep2 = await launch(cfg)
        try:
            stored = [
                r for r in dep2.replicas.values() if r.repository
            ]
            assert stored, "no replica restored its snapshot"
        finally:
            await dep2.stop()

    asyncio.run(go())
