"""Tests for auxiliary subsystems: tracing, snapshots, TLS (SURVEY §5)."""

import asyncio
import json
import ssl

import pytest

from dds_tpu.utils.trace import Tracer


# ------------------------------------------------------------------- tracing


def test_tracer_spans_and_summary():
    t = Tracer()
    for _ in range(3):
        with t.span("abd.fetch", key="k"):
            pass
    t.count("abd.suspect", 2)
    s = t.summary()
    assert s["abd.fetch"]["count"] == 3
    assert s["abd.fetch"]["p95_ms"] >= 0
    # counters are occurrences, not durations: reported via counters(),
    # never mixed into the span summary (PR 2 split the two)
    assert "abd.suspect" not in s
    assert t.counters()["abd.suspect"] == 2
    assert len(t.events("abd.fetch")) == 3


def test_tracer_disabled_records_nothing():
    t = Tracer(enabled=False)
    with t.span("x"):
        pass
    t.count("y")
    assert t.summary() == {}


def test_tracer_dump_jsonl(tmp_path):
    t = Tracer()
    with t.span("a", foo=1):
        pass
    p = tmp_path / "trace.jsonl"
    assert t.dump_jsonl(str(p)) == 1
    rec = json.loads(p.read_text().strip())
    # meta lives under its own key so span meta can never shadow the
    # record's fields (PR 2 namespaced it)
    assert rec["name"] == "a" and rec["meta"]["foo"] == 1


def test_tracer_bounded():
    t = Tracer(max_events=10)
    for i in range(25):
        t.record("e", 1.0)
    assert len(t.events()) == 10


# ----------------------------------------------------------------- snapshots


def test_snapshot_roundtrip(tmp_path):
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.messages import ABDTag
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    net = InMemoryNet()
    addrs = ["r0", "r1"]
    node = BFTABDNode("r0", addrs, "sup", net, ReplicaConfig(quorum_size=1))
    node.repository["k1"] = (ABDTag(3, "r0"), [1, "a", 2])
    node.repository["k2"] = (ABDTag(1, "r1"), None)
    node.incoming[12345] = True
    node.incoming[99] = False

    snap.save_replica(node, tmp_path)

    fresh = BFTABDNode("r0", addrs, "sup", InMemoryNet(), ReplicaConfig(quorum_size=1))
    assert snap.load_replica(fresh, tmp_path)
    assert fresh.repository["k1"] == (ABDTag(3, "r0"), [1, "a", 2])
    assert fresh.repository["k2"] == (ABDTag(1, "r1"), None)
    assert fresh.incoming[12345] is True
    # v2 persists the FULL anti-replay map: an in-flight (unexpired) nonce
    # must survive the round trip or it becomes replayable after restore
    assert fresh.incoming[99] is False


def test_snapshot_load_missing(tmp_path):
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    node = BFTABDNode("rX", ["rX"], "sup", InMemoryNet(), ReplicaConfig(quorum_size=1))
    assert not snap.load_replica(node, tmp_path)


def test_snapshot_save_all_load_all(tmp_path):
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.messages import ABDTag
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    net = InMemoryNet()
    addrs = ["r0", "r1", "r2"]
    replicas = {
        a: BFTABDNode(a, addrs, "sup", net, ReplicaConfig(quorum_size=2))
        for a in addrs
    }
    replicas["r1"].repository["k"] = (ABDTag(7, "r1"), ["x"])
    assert snap.save_all(replicas, tmp_path) == 3
    fresh = {
        a: BFTABDNode(a, addrs, "sup", InMemoryNet(), ReplicaConfig(quorum_size=2))
        for a in addrs
    }
    assert snap.load_all(fresh, tmp_path) == 3
    assert fresh["r1"].repository["k"] == (ABDTag(7, "r1"), ["x"])


# ----------------------------------------------------------------------- TLS


def test_tls_cert_generation_and_contexts(tmp_path):
    from dds_tpu.utils import tlsutil

    paths = tlsutil.generate_ca_and_cert(tmp_path, hosts=("127.0.0.1", "localhost"))
    for p in paths.values():
        assert p.exists()
    # idempotent
    again = tlsutil.generate_ca_and_cert(tmp_path)
    assert again == paths

    srv = tlsutil.server_context(paths["cert"], paths["key"], paths["ca"])
    assert srv.verify_mode == ssl.CERT_REQUIRED
    cli = tlsutil.client_context(paths["ca"], paths["cert"], paths["key"])
    assert cli.check_hostname is False


def test_mutual_tls_http_roundtrip(tmp_path):
    """Full mutual-TLS HTTP round trip through the miniserver."""
    from dds_tpu.http.miniserver import HttpServer, Response, http_request
    from dds_tpu.utils import tlsutil

    paths = tlsutil.generate_ca_and_cert(tmp_path)
    srv_ctx = tlsutil.server_context(paths["cert"], paths["key"], paths["ca"])
    cli_ctx = tlsutil.client_context(paths["ca"], paths["cert"], paths["key"])

    async def go():
        async def handler(req):
            return Response.text("secure-ok")

        server = HttpServer("127.0.0.1", 0, handler, srv_ctx)
        await server.start()
        try:
            status, body = await http_request(
                "127.0.0.1", server.port, "GET", "/", ssl_context=cli_ctx, timeout=5.0
            )
            assert status == 200 and body == b"secure-ok"
            # a client WITHOUT a cert is rejected by mutual auth
            anon = tlsutil.client_context(paths["ca"])
            with pytest.raises((ssl.SSLError, OSError, asyncio.TimeoutError)):
                await http_request(
                    "127.0.0.1", server.port, "GET", "/", ssl_context=anon, timeout=5.0
                )
        finally:
            await server.stop()

    asyncio.run(go())


def test_launch_with_tls_and_snapshots(tmp_path):
    """Boot the full deployment with TLS + snapshots enabled, run a client
    op over HTTPS, snapshot, and restore into a fresh boot."""
    import secrets

    from dds_tpu.core import snapshot as snap
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.run import launch
    from dds_tpu.utils.config import DDSConfig

    async def go():
        cfg = DDSConfig()
        cfg.security.tls_enabled = True
        cfg.security.tls_dir = str(tmp_path / "certs")
        cfg.recovery.snapshot_dir = str(tmp_path / "snaps")
        cfg.recovery.enabled = False
        cfg.proxy.port = 0
        dep = await launch(cfg)
        try:
            body = json.dumps({"contents": [1, 2, 3]}).encode()
            status, key = await http_request(
                "127.0.0.1", dep.server.cfg.port, "POST", "/PutSet", body,
                ssl_context=dep.ssl_client, timeout=10.0,
            )
            assert status == 200
            snap.save_all(dep.replicas, cfg.recovery.snapshot_dir)
        finally:
            await dep.stop()

        # fresh boot restores the snapshots
        dep2 = await launch(cfg)
        try:
            stored = [
                r for r in dep2.replicas.values() if r.repository
            ]
            assert stored, "no replica restored its snapshot"
        finally:
            await dep2.stop()

    asyncio.run(go())


# ------------------------------------------- config + transport hardening


def test_default_toml_parses_and_is_production_safe():
    """The shipped catalog config must be deployment-safe: fault injection
    OFF by default (replicas then ignore Trudy's Crash/Compromise control
    messages — the dataclass default, which the catalog previously
    overrode to True)."""
    import pathlib

    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig.load(
        pathlib.Path(__file__).resolve().parent.parent / "configs/default.toml"
    )
    assert cfg.attacks.enabled is False
    assert cfg.client.fast_blinding is True
    assert cfg.transport.advertise == ""


def test_tcpnet_advertised_address():
    from dds_tpu.core.transport import TcpNet

    net = TcpNet("0.0.0.0", 2552)
    assert net.advertised == "0.0.0.0:2552"
    assert TcpNet("0.0.0.0", 2552, advertise="10.0.0.9").advertised == "10.0.0.9:2552"
    assert (
        TcpNet("0.0.0.0", 2552, advertise="10.0.0.9:9999").advertised
        == "10.0.0.9:9999"
    )
    assert (
        TcpNet("0.0.0.0", 2552, advertise="edge.example:2552").local_addr("r-0")
        == "edge.example:2552/r-0"
    )


def test_launch_rejects_unregistered_advertised_address(tmp_path):
    """With per-node identity on, a process whose advertised address is not
    in node_public_keys would emit frames no peer can verify (and, bound to
    0.0.0.0, would itself reject every signed inbound frame) — launch()
    must fail fast instead of deploying a silently deaf fabric."""
    from dds_tpu.run import launch
    from dds_tpu.utils import nodeauth
    from dds_tpu.utils.config import DDSConfig

    async def go():
        key = nodeauth.generate()
        cfg = DDSConfig()
        cfg.transport.kind = "tcp"
        cfg.transport.port = 0
        cfg.transport.host = "127.0.0.1"
        cfg.recovery.enabled = False
        cfg.proxy.port = 0
        cfg.security.node_key_path = str(tmp_path / "node.key")
        # registry names an address this process does NOT advertise
        cfg.security.node_public_keys = {
            "10.9.9.9:2552": nodeauth.public_hex(key)
        }
        with pytest.raises(ValueError, match="advertised"):
            await launch(cfg)

    asyncio.run(go())


def test_undecodable_frame_does_not_kill_connection():
    """A malformed frame (bad JSON, unknown message type) must be dropped
    per-frame — not tear down the shared cached connection and lose every
    queued frame behind it (rolling-upgrade safety)."""
    from dds_tpu.core import messages as M
    from dds_tpu.core.transport import TcpNet

    async def go():
        net = TcpNet("127.0.0.1", 0)
        await net.start()
        got = []

        async def handler(src, msg):
            got.append(msg)

        net.register("sink", handler)
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", net.port)

            def frame(raw: bytes) -> bytes:
                return len(raw).to_bytes(4, "big") + raw

            good = json.dumps(
                {
                    "src": "peer",
                    "dest": "sink",
                    "msg": M.to_dict(M.Redeploy("replica-0")),
                }
            ).encode()
            writer.write(frame(b"this is not json"))
            writer.write(frame(json.dumps({"src": "p"}).encode()))  # missing keys
            writer.write(  # type-confused fields must not escape the guard
                frame(json.dumps({"src": "p", "dest": 123, "msg": {}}).encode())
            )
            writer.write(frame(json.dumps(["a", "list"]).encode()))
            writer.write(
                frame(
                    json.dumps(
                        {"src": "p", "dest": "sink", "msg": {"__msg__": "Nope"}}
                    ).encode()
                )
            )
            writer.write(frame(good))  # must still arrive on the SAME conn
            await writer.drain()
            for _ in range(100):
                if got:
                    break
                await asyncio.sleep(0.02)
            assert got and isinstance(got[0], M.Redeploy)
            writer.close()
        finally:
            await net.stop()

    asyncio.run(go())


def test_fast_blinding_knob_and_scaled_s_bits():
    from dds_tpu.models.paillier import PaillierPublicKey
    from dds_tpu.run import load_provider
    from dds_tpu.utils.config import DDSConfig

    cfg = DDSConfig()
    cfg.client.paillier_bits = 1024
    cfg.client.rsa_bits = 1024
    cfg.client.fast_blinding = False
    assert load_provider(cfg).fast_blinding is False
    cfg.client.fast_blinding = True
    assert load_provider(cfg).fast_blinding is True

    # s_bits scales with the modulus strength instead of a fixed 448
    assert PaillierPublicKey(1 << 2047)._djn_s_bits() == 448
    assert PaillierPublicKey(1 << 3071)._djn_s_bits() == 512
    assert PaillierPublicKey(1 << 4095)._djn_s_bits() == 608
    assert PaillierPublicKey(1 << 1023)._djn_s_bits() == 320


def test_workload_bulk_encrypt_backend_batches_obfuscators():
    """client.bulk-encrypt-backend routes a digest's PSSE obfuscator
    modexps through ONE batched backend dispatch (full-width exponent),
    and the workload still completes — the encrypt-grade modexp wiring of
    r4 verdict #3, driven through launch() + run_workload()."""
    import asyncio as _asyncio

    from dds_tpu.run import launch, load_provider, run_workload
    from dds_tpu.utils.config import DDSConfig

    async def go():
        cfg = DDSConfig()
        cfg.recovery.enabled = False
        cfg.proxy.port = 0
        cfg.client.nr_of_operations = 100
        cfg.client.paillier_bits = 512
        cfg.client.rsa_bits = 512
        cfg.client.bulk_encrypt_backend = "tpu"
        cfg.client.proportions = {"put-set": 0.9, "sum-all": 0.1}
        provider = load_provider(cfg)
        be = provider.bulk_backend
        assert be is not None and be.name == "tpu"
        be.min_device_batch = 0
        calls = []
        orig = be.powmod_batch
        be.powmod_batch = lambda bases, exp, mod: calls.append(
            (len(bases), exp.bit_length())
        ) or orig(bases, exp, mod)

        dep = await launch(cfg)
        try:
            reports = await run_workload(dep, provider=provider, seed=3)
        finally:
            await dep.stop()
        assert all(r.failed == 0 for r in reports)
        # one batched dispatch, full-width (n-bit) exponent, >= min_batch rows
        assert calls and calls[0][0] >= 60 and calls[0][1] >= 511
        assert len(provider._blind_pool) == 0  # drained by the PutSets

    _asyncio.run(go())
