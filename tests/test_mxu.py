"""Known-answer tests for the hybrid VPU+MXU Montgomery multiply (v2).

Exactness is the whole game: every stage (carry normalization, schoolbook
product, band-matmul reduction, full multiply, fold) is compared against
python int arithmetic. Runs in Pallas interpret mode on the CPU mesh
(tests/conftest.py); the same code paths compile for TPU.
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from dds_tpu.ops import bignum as bn
from dds_tpu.ops import mont_mxu as mx
from dds_tpu.ops.montgomery import ModCtx


def _rand_mod(rng, bits):
    while True:
        n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
        if n % 2:
            return n


def _to_lm(vals, L):
    return jnp.asarray(bn.ints_to_batch(vals, L)).T


def _from_lm(x):
    return bn.batch_to_ints(np.asarray(x).T)


def test_carry_norm_preserves_value_16():
    rng = np.random.default_rng(0)
    rows, B = 24, 3
    x = rng.integers(0, 1 << 31, size=(rows, B), dtype=np.uint32)
    digits, carry = mx.carry_norm(jnp.asarray(x))
    digits, carry = np.asarray(digits), np.asarray(carry)
    for b in range(B):
        want = sum(int(x[k, b]) << (16 * k) for k in range(rows))
        got = sum(int(digits[k, b]) << (16 * k) for k in range(rows))
        got += int(carry[0, b]) << (16 * rows)
        assert got == want
        assert digits[:, b].max() <= 0xFFFF


def test_carry_norm_preserves_value_8():
    rng = np.random.default_rng(1)
    rows, B = 32, 2
    x = rng.integers(0, 1 << 25, size=(rows, B), dtype=np.uint32)
    digits, carry = mx.carry_norm(jnp.asarray(x), bits=8)
    digits, carry = np.asarray(digits), np.asarray(carry)
    for b in range(B):
        want = sum(int(x[k, b]) << (8 * k) for k in range(rows))
        got = sum(int(digits[k, b]) << (8 * k) for k in range(rows))
        got += int(carry[0, b]) << (8 * rows)
        assert got == want
        assert digits[:, b].max() <= 0xFF


def test_prod_lm_matches_python():
    rng = random.Random(2)
    L = 32  # 512-bit operands
    vals_a = [rng.getrandbits(16 * L) for _ in range(4)]
    vals_b = [rng.getrandbits(16 * L) for _ in range(4)]
    T = mx.prod_lm(_to_lm(vals_a, L), _to_lm(vals_b, L), interpret=True)
    digits, carry = mx.carry_norm(T)
    assert int(np.asarray(carry).max()) == 0
    got = _from_lm(digits)
    for g, a, b in zip(got, vals_a, vals_b):
        assert g == a * b


def test_mul2_odd_limb_count():
    """Moduli whose limb count is not a multiple of the kernel's GROUP
    (e.g. 520-bit -> L=33) must work via zero-padded limbs."""
    rng = random.Random(33)
    n = _rand_mod(rng, 520)
    ctx = ModCtx.make(n)
    assert ctx.L % mx.GROUP != 0
    mctx = mx.MxuCtx.make(ctx)
    R = 1 << (16 * ctx.L)
    Rinv = pow(R, -1, n)
    vals_a = [rng.randrange(n) for _ in range(3)]
    vals_b = [rng.randrange(n) for _ in range(3)]
    out = mx.mul2_lm(
        mctx, _to_lm(vals_a, ctx.L), _to_lm(vals_b, ctx.L), interpret=True
    )
    for g, a, b in zip(_from_lm(out), vals_a, vals_b):
        assert g == (a * b * Rinv) % n


@pytest.mark.parametrize("bits", [512, 1024])
def test_mul2_matches_python(bits):
    rng = random.Random(bits)
    n = _rand_mod(rng, bits)
    ctx = ModCtx.make(n)
    mctx = mx.MxuCtx.make(ctx)
    R = 1 << (16 * ctx.L)
    Rinv = pow(R, -1, n)
    vals_a = [rng.randrange(n) for _ in range(5)] + [0, n - 1]
    vals_b = [rng.randrange(n) for _ in range(5)] + [n - 1, n - 1]
    out = mx.mul2_lm(
        mctx, _to_lm(vals_a, ctx.L), _to_lm(vals_b, ctx.L), interpret=True
    )
    for g, a, b in zip(_from_lm(out), vals_a, vals_b):
        assert g == (a * b * Rinv) % n


def test_reduce_mul2_matches_python_and_v1():
    from dds_tpu.ops import pallas_mont as pm

    rng = random.Random(7)
    n = _rand_mod(rng, 512)
    ctx = ModCtx.make(n)
    mctx = mx.MxuCtx.make(ctx)
    for K in (1, 2, 3, 7, 16):
        cs = [rng.randrange(n) for _ in range(K)]
        want = 1
        for c in cs:
            want = want * c % n
        batch = bn.ints_to_batch(cs, ctx.L)
        got2 = bn.batch_to_ints(np.asarray(mx.reduce_mul2(mctx, batch, interpret=True)))[0]
        assert got2 == want, f"v2 fold wrong at K={K}"
        got1 = bn.batch_to_ints(np.asarray(pm.reduce_mul(ctx, batch, interpret=True)))[0]
        assert got1 == want, f"v1 fold wrong at K={K}"


@pytest.mark.parametrize("bits,ebits", [(256, 17), (256, 64), (512, 130)])
def test_pow_mod2_matches_python(bits, ebits):
    """v2 windowed modexp ladder (table + scan over mul2_lm) vs pow()."""
    import random

    from dds_tpu.ops import mont_mxu as mx

    rng = random.Random(bits * 1000 + ebits)
    n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    ctx = ModCtx.make(n)
    mctx = mx.MxuCtx.make(ctx)
    bases = [rng.randrange(1, n) for _ in range(5)]
    exp = rng.getrandbits(ebits) | 1
    out = mx.pow_mod2(mctx, bn.ints_to_batch(bases, ctx.L), exp)
    assert bn.batch_to_ints(np.asarray(out)) == [pow(b, exp, n) for b in bases]


def test_pow_mod2_zero_exponent():
    import random

    from dds_tpu.ops import mont_mxu as mx

    rng = random.Random(77)
    n = rng.getrandbits(256) | (1 << 255) | 1
    ctx = ModCtx.make(n)
    mctx = mx.MxuCtx.make(ctx)
    bases = [rng.randrange(1, n) for _ in range(3)]
    out = mx.pow_mod2(mctx, bn.ints_to_batch(bases, ctx.L), 0)
    assert bn.batch_to_ints(np.asarray(out)) == [1, 1, 1]


@pytest.mark.parametrize("bits", [256, 512])
def test_prod_lm_k1_matches_python(bits):
    """Karatsuba product variant: exact full products, any even L."""
    import random

    rng = random.Random(bits)
    L = bn.n_limbs_for_bits(bits)
    xs = [rng.getrandbits(bits) for _ in range(3)]
    ys = [rng.getrandbits(bits) for _ in range(3)]
    T = np.asarray(mx.prod_lm_k1(bn.ints_to_batch(xs, L).T,
                                 bn.ints_to_batch(ys, L).T))
    for i in range(3):
        val = sum(int(d) << (16 * k) for k, d in enumerate(T[:, i]))
        assert val == xs[i] * ys[i]


def test_reduce_mul2_karatsuba_flag(monkeypatch):
    """DDS_KARATSUBA=1 routes mul2 through prod_lm_k1 with identical
    results."""
    import random

    monkeypatch.setenv("DDS_KARATSUBA", "1")
    rng = random.Random(31)
    n = rng.getrandbits(512) | (1 << 511) | 1
    ctx = ModCtx.make(n)
    mctx = mx.MxuCtx.make(ctx)
    cs = [rng.randrange(n) for _ in range(8)]
    out = mx.reduce_mul2(mctx, bn.ints_to_batch(cs, ctx.L))
    want = 1
    for c in cs:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


def test_prod_lm_kf_fused_karatsuba_matches_int():
    """The fully in-kernel Karatsuba product (three half products + the
    whole recombination in ONE Pallas kernel) must equal python ints."""
    import random

    rng = random.Random(91)
    for bits in (256, 512):
        L = bits // 16
        xs = [rng.getrandbits(bits) for _ in range(4)]
        ys = [rng.getrandbits(bits) for _ in range(4)]
        T = np.asarray(
            mx.prod_lm_kf(bn.ints_to_batch(xs, L).T, bn.ints_to_batch(ys, L).T)
        )
        for i in range(4):
            val = sum(int(d) << (16 * k) for k, d in enumerate(T[:, i]))
            assert val == xs[i] * ys[i]


def test_reduce_mul2_fused_karatsuba_flag(monkeypatch):
    """DDS_KARATSUBA=2 routes mul2 through the fused kernel with
    identical results (incl. the modexp ladder)."""
    import random

    monkeypatch.setenv("DDS_KARATSUBA", "2")
    rng = random.Random(92)
    n = rng.getrandbits(512) | (1 << 511) | 1
    ctx = ModCtx.make(n)
    mctx = mx.MxuCtx.make(ctx)
    cs = [rng.randrange(n) for _ in range(11)]
    out = mx.reduce_mul2(mctx, bn.ints_to_batch(cs, ctx.L))
    want = 1
    for c in cs:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want
    bases = [rng.randrange(n) for _ in range(4)]
    exp = rng.getrandbits(40)
    got = mx.pow_mod2(mctx, bn.ints_to_batch(bases, ctx.L), exp)
    assert bn.batch_to_ints(np.asarray(got)) == [pow(b, exp, n) for b in bases]
