"""Tier-1 scheme tests: roundtrips, homomorphisms, backend parity.

Covers what the reference exercises through `SJHomoLibProvider` plus the
properties its proxy relies on (det compare, OPE ordering, ciphertext
add/mult), against both crypto backends.
"""

import random

import pytest

from dds_tpu.models import HEKeys, HomoProvider, get_backend
from dds_tpu.models.facade import DEFAULT_SCHEMA
from dds_tpu.models.paillier import PaillierKey
from dds_tpu.models.mult import RsaMultKey

rng = random.Random(1)

# Small keys keep CPU-mesh tests fast; key-size sweeps happen in bench.
KEYS = HEKeys.generate(paillier_bits=512, rsa_bits=512)
PROVIDER = HomoProvider(KEYS)


def test_paillier_roundtrip_and_add():
    pk = KEYS.psse.public
    for _ in range(5):
        a, b = rng.randrange(1 << 31), rng.randrange(1 << 31)
        ca, cb = pk.encrypt(a), pk.encrypt(b)
        assert ca != cb
        assert KEYS.psse.decrypt(ca) == a
        assert KEYS.psse.decrypt(pk.add(ca, cb)) == a + b
        assert KEYS.psse.decrypt(pk.scalar_mul(ca, 7)) == 7 * a


def test_paillier_negative():
    pk = KEYS.psse.public
    assert KEYS.psse.decrypt_signed(pk.encrypt(-42)) == -42
    c = pk.add(pk.encrypt(-42), pk.encrypt(40))
    assert KEYS.psse.decrypt_signed(c) == -2


def test_rsa_mult():
    k = KEYS.mse
    a, b = 1234567, 89012
    prod = k.public.mult(k.public.encrypt(a), k.public.encrypt(b))
    assert k.decrypt(prod) == a * b


def test_ope_order_and_roundtrip():
    k = KEYS.ope
    xs = sorted(rng.sample(range(-(1 << 31), 1 << 31), 50))
    cs = [k.encrypt(x) for x in xs]
    assert cs == sorted(cs)
    assert [k.decrypt(c) for c in cs] == xs
    with pytest.raises(ValueError):
        k.encrypt(1 << 40)
    with pytest.raises(ValueError):
        k.decrypt(cs[0] + 1)


def test_det_deterministic():
    k = KEYS.che
    c1, c2 = k.encrypt("hello"), k.encrypt("hello")
    assert c1 == c2 and k.compare(c1, c2)
    assert not k.compare(c1, k.encrypt("world"))
    assert k.decrypt(c1) == "hello"


def test_searchable():
    k = KEYS.lse
    c = k.encrypt("the quick brown fox")
    assert k.decrypt(c) == "the quick brown fox"
    assert k.matches(c, k.trapdoor("quick"))
    assert not k.matches(c, k.trapdoor("slow"))


def test_rand_probabilistic():
    k = KEYS.none
    c1, c2 = k.encrypt("same"), k.encrypt("same")
    assert c1 != c2
    assert k.decrypt(c1) == k.decrypt(c2) == "same"


def test_key_serialization_roundtrip():
    blob = KEYS.to_json()
    back = HEKeys.from_json(blob)
    assert back == KEYS
    # loaded keys decrypt what original keys encrypted
    c = KEYS.psse.public.encrypt(99)
    assert back.psse.decrypt(c) == 99
    assert back.che.decrypt(KEYS.che.encrypt("x")) == "x"


def test_row_roundtrip_default_schema():
    row = [41, "bob", 1500, 3, "eng", "lisbon", "blue", "free text tail", "more"]
    enc = PROVIDER.encrypt_row(row, 8, DEFAULT_SCHEMA)
    assert len(enc) == len(row)
    assert enc[0] != row[0] and isinstance(enc[0], int)
    dec = PROVIDER.decrypt_row(enc, 8, DEFAULT_SCHEMA)
    assert dec == [41, "bob", 1500, 3, "eng", "lisbon", "blue", "free text tail", "more"]


def test_unknown_scheme_tag():
    with pytest.raises(ValueError):
        PROVIDER.encrypt(1, "XYZ")


def _backend(name):
    """tpu tests must exercise the DEVICE fold even on small batches, not
    the adaptive host fallback (min_device_batch defaults to 1024)."""
    be = get_backend(name)
    if name == "tpu":
        be.min_device_batch = 0
    return be


@pytest.mark.parametrize("backend_name", ["cpu", "tpu"])
def test_backend_paillier_sum(backend_name):
    be = _backend(backend_name)
    pk = KEYS.psse.public
    vals = [rng.randrange(1 << 20) for _ in range(9)]
    cs = [pk.encrypt(v) for v in vals]
    total = be.modmul_fold(cs, pk.nsquare)
    assert KEYS.psse.decrypt(total) == sum(vals)
    pair = be.modmul(cs[0], cs[1], pk.nsquare)
    assert KEYS.psse.decrypt(pair) == vals[0] + vals[1]


@pytest.mark.parametrize("backend_name", ["cpu", "tpu"])
def test_backend_rsa_product(backend_name):
    be = _backend(backend_name)
    k = KEYS.mse
    vals = [rng.randrange(1 << 8) for _ in range(5)]
    cs = [k.public.encrypt(v) for v in vals]
    prod = be.modmul_fold(cs, k.n)
    want = 1
    for v in vals:
        want *= v
    assert k.decrypt(prod) == want


def test_backend_powmod_parity():
    cpu, tpu = get_backend("cpu"), get_backend("tpu")
    n = KEYS.mse.n
    bases = [rng.randrange(n) for _ in range(4)]
    assert cpu.powmod_batch(bases, 65537, n) == tpu.powmod_batch(bases, 65537, n)


def test_unknown_backend():
    with pytest.raises(ValueError):
        get_backend("gpu")


def test_generator_proportions_replace_defaults():
    from dds_tpu.clt.generator import generate

    ops = generate(100, {"put-set": 0.5, "get-set": 0.5}, rng=random.Random(1))
    assert len(ops) == 100  # nothing leaks in from the defaults
    with pytest.raises(ValueError):
        generate(10, {"no-such-op": 1.0})


def test_searchable_trapdoor_nonce_domain_separation():
    k = KEYS.lse
    # the public trapdoor of a 'siv|'-prefixed word must not equal the
    # nonce component of any record's ciphertext
    c = k.encrypt("alice")
    nonce_field = c.split(".")[0]
    assert k.trapdoor("siv|alice") != nonce_field[: len(k.trapdoor("siv|alice"))]


# ------------------------------------------ bulk (encrypt-grade) modexp path

def test_paillier_encrypt_batch_decrypts_through_tpu_backend():
    """Bulk encryption routed through TpuBackend.powmod_batch with the
    FULL-WIDTH n-bit exponent (the encrypt-grade modexp of r4 verdict #3):
    every ciphertext must decrypt, obfuscators must be fresh per message."""
    pk = KEYS.psse.public
    be = get_backend("tpu")
    be.min_device_batch = 0
    ms = [rng.randrange(1 << 32) for _ in range(9)]
    cts = pk.encrypt_batch(ms, backend=be, min_batch=1)
    assert [KEYS.psse.decrypt(c) for c in cts] == ms
    # same message twice -> different ciphertexts (independent obfuscators)
    c1, c2 = pk.encrypt_batch([7, 7], backend=be, min_batch=1)
    assert c1 != c2 and KEYS.psse.decrypt(c1) == KEYS.psse.decrypt(c2) == 7
    # below min_batch: host loop, same contract
    cts_host = pk.encrypt_batch(ms, backend=be, min_batch=10_000)
    assert [KEYS.psse.decrypt(c) for c in cts_host] == ms


def test_provider_blind_pool_feeds_psse_encrypts():
    """precompute_psse_blinds fills the pool via the bulk backend; PSSE
    encrypts drain it (fresh obfuscator each) and fall back to the DJN
    path once empty."""
    be = get_backend("tpu")
    be.min_device_batch = 0
    prov = HomoProvider(KEYS, bulk_backend=be)
    assert prov.precompute_psse_blinds(4, min_batch=1) == 4
    assert len(prov._blind_pool) == 4
    cts = [int(prov.encrypt(i, "PSSE")) for i in range(5)]  # 4 pooled + 1 DJN
    assert len(prov._blind_pool) == 0
    assert [KEYS.psse.decrypt(c) for c in cts] == list(range(5))
    # no backend -> precompute is a no-op and per-op paths serve
    prov2 = HomoProvider(KEYS)
    assert prov2.precompute_psse_blinds(100) == 0
    assert KEYS.psse.decrypt(int(prov2.encrypt(42, "PSSE"))) == 42


def test_paillier_decrypt_batch_through_sanctum():
    """Batched CRT decrypt routes through the Sanctum secret plane: the
    fused two-leg device dispatch matches the per-op host decrypt
    bit-for-bit, and the public-parameter backends the old contract
    accepted are refused loudly (the ADVICE.md medium finding, closed at
    the source)."""
    from dds_tpu.sanctum import SecretBackend

    pk = KEYS.psse.public
    ms = [rng.randrange(1 << 40) for _ in range(7)]
    cts = [pk.encrypt(m) for m in ms]
    dev = SecretBackend(device=True)
    assert KEYS.psse.decrypt_batch(cts, backend=dev, min_batch=1) == ms
    # host plan (below min_batch, or no backend) agrees
    assert KEYS.psse.decrypt_batch(cts, backend=dev, min_batch=100) == ms
    assert KEYS.psse.decrypt_batch(cts) == ms
    # a public CryptoBackend can no longer carry the secret CRT legs
    with pytest.raises(ValueError, match="public-parameter"):
        KEYS.psse.decrypt_batch(cts, backend=get_backend("tpu"), min_batch=1)
    with pytest.raises(ValueError, match="public-parameter"):
        KEYS.psse.decrypt_batch(cts, backend=get_backend("cpu"))


def test_provider_decrypt_rows_batches_psse_columns():
    """decrypt_rows batches every PSSE column through one Sanctum CRT
    pass and matches per-row decrypt_row exactly (incl. the signed
    mapping for negative values) — and the PUBLIC bulk backend, now
    encrypt-only, is never consulted on the decrypt path."""
    from dds_tpu.sanctum import SecretBackend

    be = get_backend("tpu")
    be.min_device_batch = 0
    prov = HomoProvider(
        KEYS, bulk_backend=be, secret_backend=SecretBackend(device=True)
    )
    # numeric schemes only (no CHE/None): the behavior under test is
    # PSSE batching, and this keeps the test running in AES-less envs
    schema = ["OPE", "MSE", "PSSE", "PSSE"]
    rows_plain = [[i, i * 7 + 1, i * 1000, -i] for i in range(6)]
    rows_enc = [prov.encrypt_row(list(r), 4, schema) for r in rows_plain]
    calls = {"n": 0}
    orig = be.powmod_batch
    be.powmod_batch = lambda b, e, m: calls.__setitem__("n", calls["n"] + 1) or orig(b, e, m)
    got = prov.decrypt_rows(rows_enc, 4, schema, min_batch=1)
    assert calls["n"] == 0  # secret CRT legs never touch the public backend
    want = [prov.decrypt_row(r, 4, schema) for r in rows_enc]
    assert got == want
    assert [g[:4] for g in got] == rows_plain
    # without any backend: identical results through the host-only plane
    assert HomoProvider(KEYS).decrypt_rows(rows_enc, 4, schema) == want
    # below min_batch: the per-row path, same results
    assert prov.decrypt_rows(rows_enc, 4, schema, min_batch=10_000) == want
