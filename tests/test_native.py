"""Parity tests for the native C++ bignum runtime (dds_tpu.native).

Every entry point is checked against python big-int arithmetic, including
the graceful-fallback paths (even modulus, exp 0, empty fold). When the
toolchain is unavailable the module must still answer correctly via the
python fallback — so these tests never skip.
"""

import random

import pytest

from dds_tpu import native


@pytest.fixture(scope="module")
def rng():
    return random.Random(0xBEEF)


@pytest.mark.parametrize("bits", [64, 256, 1024, 2048, 4096])
def test_powmod_parity(rng, bits):
    n = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    for _ in range(3):
        b, e = rng.randrange(n), rng.getrandbits(bits)
        assert native.powmod(b, e, n) == pow(b, e, n)


def test_powmod_edges(rng):
    n = rng.getrandbits(256) | (1 << 255) | 1
    assert native.powmod(0, 5, n) == 0
    assert native.powmod(5, 0, n) == 1
    assert native.powmod(5, 1, n) == 5
    assert native.powmod(n + 7, 3, n) == pow(n + 7, 3, n)
    # even modulus falls back to python pow
    assert native.powmod(5, 3, 96) == pow(5, 3, 96)
    # negative exponent (modular inverse) falls back
    assert native.powmod(5, -1, 97) == pow(5, -1, 97)


def test_powmod_batch(rng):
    n = rng.getrandbits(1024) | (1 << 1023) | 1
    bases = [rng.randrange(n) for _ in range(7)]
    e = rng.getrandbits(1024)
    assert native.powmod_batch(bases, e, n) == [pow(b, e, n) for b in bases]
    assert native.powmod_batch([], e, n) == []


@pytest.mark.parametrize("K", [1, 2, 3, 17])
def test_fold(rng, K):
    n = rng.getrandbits(2048) | (1 << 2047) | 1
    cs = [rng.randrange(1, n) for _ in range(K)]
    want = 1
    for c in cs:
        want = want * c % n
    assert native.fold(cs, n) == want


def test_fold_empty():
    assert native.fold([], 97) == 1


def test_native_backend_matches_cpu(rng):
    from dds_tpu.models.backend import CpuBackend, get_backend

    n = rng.getrandbits(512) | (1 << 511) | 1
    cs = [rng.randrange(1, n) for _ in range(9)]
    nat, cpu = get_backend("native"), CpuBackend()
    assert nat.modmul_fold(cs, n) == cpu.modmul_fold(cs, n)
    assert nat.powmod_batch(cs[:3], 65537, n) == cpu.powmod_batch(cs[:3], 65537, n)
    assert nat.modmul(cs[0], cs[1], n) == cpu.modmul(cs[0], cs[1], n)


def test_paillier_roundtrip_uses_native():
    # end-to-end: encrypt/decrypt on the powmod-routed path
    from dds_tpu.models.paillier import PaillierKey

    key = PaillierKey.generate(512)
    c = key.public.encrypt(123456)
    assert key.decrypt(c) == 123456
    c2 = key.public.scalar_mul(c, 3)
    assert key.decrypt(c2) == 123456 * 3
