"""Chronoscope tests: critical-path extraction over span trees (linear,
parallel fan-out, orphaned/partial), the attribution-coverage property on
REAL traces from a seeded ChaosNet cluster, the per-route aggregate +
gauge surface, the TimedQueue telemetry shared by the ingest queues, the
kprof compile/dispatch split, the Panopticon fleet-profile rollup, and
the sentry `pipe profile` record contract.
"""

import asyncio
import json
import time
from types import SimpleNamespace

import pytest

from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.quorum_client import AbdClient, AbdClientConfig
from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.obs.chronoscope import (
    STAGES, Chronoscope, classify, critical_path,
)
from dds_tpu.obs.metrics import Registry
from dds_tpu.utils.queues import TimedQueue
from dds_tpu.utils.trace import SpanRecord, tracer

pytestmark = pytest.mark.obs


def run(coro):
    return asyncio.run(coro)


def S(name, start, end, span_id, parent_id=None, tid="t1", kind="span",
      **meta):
    """A synthetic SpanRecord: ts is the END instant (spans record on
    exit), dur covers [start, end] in seconds."""
    return SpanRecord(ts=end, name=name, dur_ms=(end - start) * 1e3,
                      meta=meta, trace_id=tid, span_id=span_id,
                      parent_id=parent_id, kind=kind)


# ------------------------------------------------------------ taxonomy


def test_classify_is_closed_over_stages():
    for name in ("proxy.admission", "proxy.coalesce_wait", "net.serialize",
                 "abd.verify", "abd.write", "abd.read_quorum",
                 "ingest.queue_wait", "ingest.h2d", "replica.handle",
                 "antientropy.sync", "kernel.foldmany.compile",
                 "kernel.foldmany.dispatch", "kernel.foldmany.execute",
                 "proxy.coalesced_fold", "http.POST.PutSet",
                 "proxy.get_set", "totally.unknown"):
        assert classify(name) in STAGES
    assert classify("abd.verify") == "hmac-verify"
    assert classify("abd.write") == "quorum-rtt"
    assert classify("kernel.foldmany.compile") == "trace-compile"
    assert classify("kernel.foldmany.execute") == "device-execute"
    assert classify("ingest.h2d") == "host-to-device-transfer"
    assert classify("totally.unknown") == "other"


# ------------------------------------------------- critical-path extraction


def test_linear_chain_attributes_self_times():
    """root[0,100ms] -> abd.write[10,90] -> replica.handle[20,60]: each
    level's self-time is its window minus the claimed child window, and
    the stage sums reconstruct the root wall exactly."""
    recs = [
        S("replica.handle", 0.020, 0.060, "c2", "c1"),
        S("abd.write", 0.010, 0.090, "c1", "r"),
        S("http.POST.PutSet", 0.000, 0.100, "r"),
    ]
    res = critical_path(recs)
    assert res is not None and res["route"] == "http.POST.PutSet"
    assert res["wall_ms"] == pytest.approx(100.0, abs=0.01)
    assert res["stages"]["response"] == pytest.approx(20.0, abs=0.01)
    assert res["stages"]["quorum-rtt"] == pytest.approx(40.0, abs=0.01)
    assert res["stages"]["replica-apply"] == pytest.approx(40.0, abs=0.01)
    assert sum(res["stages"].values()) == pytest.approx(100.0, abs=0.05)
    assert res["coverage"] == pytest.approx(1.0, abs=0.001)
    # the waterfall is chronological parent-then-claimed-children
    assert [e["name"] for e in res["path"]] == [
        "http.POST.PutSet", "abd.write", "replica.handle"]


def test_parallel_fanout_claims_slowest_branch():
    """Two overlapping quorum legs: the slower branch claims the window,
    the faster sibling (fully covered) contributes nothing — critical
    path semantics, not sum-of-children (which would exceed the wall)."""
    recs = [
        S("abd.write", 0.010, 0.090, "slow", "r", coordinator="replica-1"),
        S("abd.write", 0.010, 0.050, "fast", "r", coordinator="replica-2"),
        S("http.POST.PutSet", 0.000, 0.100, "r"),
    ]
    res = critical_path(recs)
    assert res["stages"]["quorum-rtt"] == pytest.approx(80.0, abs=0.01)
    assert res["stages"]["response"] == pytest.approx(20.0, abs=0.01)
    assert sum(res["stages"].values()) <= res["wall_ms"] + 0.05
    legs = [e for e in res["path"] if e["name"] == "abd.write"]
    assert len(legs) == 1 and legs[0]["meta"]["coordinator"] == "replica-1"


def test_partially_overlapping_siblings_claim_disjoint_windows():
    """Staggered siblings: the later-ending child claims its window, the
    earlier one keeps only the uncovered head — total claimed never
    exceeds the parent window."""
    recs = [
        S("abd.read_quorum", 0.000, 0.060, "a", "r"),
        S("abd.write", 0.040, 0.100, "b", "r"),
        S("http.POST.PutSet", 0.000, 0.100, "r"),
    ]
    res = critical_path(recs)
    # b claims [40,100], a keeps [0,40]: root self-time is zero
    assert res["stages"]["quorum-rtt"] == pytest.approx(100.0, abs=0.05)
    assert res["stages"].get("response", 0.0) == pytest.approx(0.0, abs=0.05)


def test_orphaned_spans_attach_to_root_clamped():
    """A span whose parent never arrived (Panopticon straggler) hangs off
    the root, clamped to the root window — a partial tree still
    attributes instead of vanishing into 'other'."""
    recs = [
        # parent "ghost" never shipped; span also overhangs the root end
        S("replica.handle", 0.050, 0.150, "x", "ghost"),
        S("http.POST.PutSet", 0.000, 0.100, "r"),
    ]
    res = critical_path(recs)
    assert res["stages"]["replica-apply"] == pytest.approx(50.0, abs=0.01)
    assert res["stages"]["response"] == pytest.approx(50.0, abs=0.01)
    # without orphan adoption the same tree attributes everything to root
    res2 = critical_path(recs, orphans_to_root=False)
    assert res2["stages"]["response"] == pytest.approx(100.0, abs=0.01)
    assert "replica-apply" not in res2["stages"]


def test_no_usable_root_returns_none():
    assert critical_path([]) is None
    assert critical_path([S("abd.write", 0.0, 0.1, "c", "gone")],
                         root_span_id="nope") is None
    # zero-duration root cannot be attributed
    assert critical_path([S("http.GET.Health", 0.5, 0.5, "r")]) is None


def test_unknown_spans_count_against_coverage():
    recs = [
        S("totally.unknown", 0.000, 0.080, "u", "r"),
        S("http.POST.PutSet", 0.000, 0.100, "r"),
    ]
    res = critical_path(recs)
    assert res["stages"]["other"] == pytest.approx(80.0, abs=0.01)
    assert res["coverage"] == pytest.approx(0.2, abs=0.001)


# ----------------------------------------------------- aggregate + surface


def _feed_trace(cs, tid, wall_s, extra=()):
    cs.on_record(S("abd.write", 0.01, wall_s - 0.01, f"{tid}-c", f"{tid}-r",
                   tid=tid))
    for rec in extra:
        cs.on_record(rec)
    cs.on_record(S("http.POST.PutSet", 0.0, wall_s, f"{tid}-r", tid=tid))


def test_chronoscope_aggregates_routes_and_exports_gauges():
    reg = Registry()
    cs = Chronoscope(registry=reg, slow_ms=1e9)
    for i, wall in enumerate((0.100, 0.080, 0.120)):
        _feed_trace(cs, f"t{i}", wall)
    prof = cs.profile()
    rs = prof["routes"]["http.POST.PutSet"]
    assert rs["count"] == 3 and prof["traces_profiled"] >= 3
    assert rs["wall_p95_ms"] == pytest.approx(120.0, abs=0.5)
    assert rs["top_stage"] == "quorum-rtt"
    assert rs["coverage"] > 0.99
    assert rs["stages"]["quorum-rtt"]["p95_ms"] > 0
    cs.export_gauges(reg)
    text = reg.render()
    assert 'dds_pipe_wall_p95_ms{route="http.POST.PutSet"}' in text
    assert 'dds_pipe_stage_p95_ms{route="http.POST.PutSet"' in text
    assert 'stage="quorum-rtt"' in text
    # folded flamegraph text carries route;stage cumulative totals
    assert "http.POST.PutSet;quorum-rtt" in cs.folded()


def test_chronoscope_keeps_worst_k_exemplars():
    cs = Chronoscope(registry=Registry(), exemplars=2, slow_ms=1e9)
    for i, wall in enumerate((0.010, 0.200, 0.020, 0.150, 0.030)):
        _feed_trace(cs, f"t{i}", wall)
    ex = cs.profile()["routes"]["http.POST.PutSet"]["exemplars"]
    walls = [e["wall_ms"] for e in ex]
    assert walls == sorted(walls, reverse=True)[:2]
    assert walls[0] == pytest.approx(200.0, abs=0.5)
    assert ex[0]["path"], "exemplars retain the waterfall"


def test_chronoscope_replica_subtree_profiled_once():
    """replica.handle subtrees are profiled as their own route when they
    land, and NOT re-absorbed when the http root closes the trace."""
    cs = Chronoscope(registry=Registry(), slow_ms=1e9)
    cs.on_record(S("replica.handle", 0.02, 0.06, "h", "c", tid="t9"))
    assert cs.profile()["routes"]["replica.handle"]["count"] == 1
    cs.on_record(S("abd.write", 0.01, 0.09, "c", "r", tid="t9"))
    cs.on_record(S("http.POST.PutSet", 0.0, 0.1, "r", tid="t9"))
    prof = cs.profile()
    assert prof["routes"]["replica.handle"]["count"] == 1
    # ...but its time still attributes inside the http route's tree
    assert prof["routes"]["http.POST.PutSet"]["stages"]["replica-apply"]


def test_chronoscope_disabled_by_env(monkeypatch):
    monkeypatch.setenv("DDS_OBS_PIPE", "0")
    cs = Chronoscope(registry=Registry())
    assert cs.enabled is False
    _feed_trace(cs, "t0", 0.1)
    assert cs.profile()["routes"] == {}


def test_ingest_tree_profiles_stitched_trace():
    cs = Chronoscope(registry=Registry(), slow_ms=1e9)
    cs.ingest_tree([
        S("replica.handle", 0.02, 0.06, "h", "c"),
        S("abd.write", 0.01, 0.09, "c", "r"),
        S("http.POST.PutSet", 0.0, 0.1, "r"),
    ])
    prof = cs.profile()
    assert prof["routes"]["http.POST.PutSet"]["count"] == 1
    assert prof["routes"]["replica.handle"]["count"] == 1


# --------------------------------------- real traces under seeded ChaosNet


async def _chaos_stack(seed=21, delay=0.001, jitter=0.002):
    net = ChaosNet(InMemoryNet(), seed=seed)
    net.default_faults = LinkFaults(delay=delay, jitter=jitter)
    addrs = [f"replica-{i}" for i in range(7)]
    replicas = {
        a: BFTABDNode(a, addrs, "supervisor", net,
                      ReplicaConfig(quorum_size=5))
        for a in addrs
    }
    abd = AbdClient("proxy-0", net, addrs,
                    AbdClientConfig(request_timeout=2.0, quorum_size=5))
    server = DDSRestServer(
        abd, ProxyConfig(host="127.0.0.1", port=0, request_budget=10.0,
                         trace_route_enabled=True))
    await server.start()
    return net, server, replicas


async def _call(server, method, target, obj=None):
    body = json.dumps(obj).encode() if obj is not None else None
    return await http_request("127.0.0.1", server.cfg.port, method, target,
                              body, timeout=10.0)


def test_attribution_coverage_on_real_chaos_traces():
    """Acceptance property: on real PutSet/GetSet traces from a seeded
    ChaosNet cluster, the critical path attributes >=95% of every
    request's wall time to NAMED stages."""
    cs = Chronoscope(registry=Registry(), slow_ms=1e9)

    async def go():
        net, server, _ = await _chaos_stack()
        try:
            tracer.reset()
            cs.attach(tracer)
            status, body = await _call(server, "POST", "/PutSet",
                                       {"contents": ["a", "b"]})
            assert status == 200
            key = bytes(body).decode()
            status, _ = await _call(server, "GET", "/GetSet/" + key)
            assert status == 200
            await net.quiesce()
        finally:
            cs.detach()
            await server.stop()

    run(go())
    roots = [e for e in tracer.events()
             if e.kind == "span" and e.parent_id is None
             and e.name.startswith("http.")]
    assert len(roots) == 2
    for root in roots:
        res = critical_path(tracer.trace_events(root.trace_id),
                            root_span_id=root.span_id)
        assert res is not None
        assert res["coverage"] >= 0.95, (root.name, res["stages"])
        # the quorum round must be visible as a named stage
        assert res["stages"].get("quorum-rtt", 0.0) > 0
    # the live-attached Chronoscope absorbed the same routes
    routes = cs.profile()["routes"]
    assert "http.POST.PutSet" in routes and "http.GET.GetSet" in routes
    assert routes["http.POST.PutSet"]["coverage"] >= 0.95


def test_injected_quorum_delay_moves_top_stage_to_quorum_rtt():
    """Acceptance: a seeded ChaosNet delay on the quorum links makes
    quorum-rtt the top stage, and the worst exemplar's waterfall carries
    the injected chaos.delay annotations."""
    cs = Chronoscope(registry=Registry(), slow_ms=1e9)

    async def go():
        net, server, _ = await _chaos_stack(seed=5, delay=0.03, jitter=0.01)
        try:
            tracer.reset()
            cs.attach(tracer)
            status, _ = await _call(server, "POST", "/PutSet",
                                    {"contents": ["x"]})
            assert status == 200
            await net.quiesce()
        finally:
            cs.detach()
            await server.stop()

    run(go())
    rs = cs.profile()["routes"]["http.POST.PutSet"]
    assert rs["top_stage"] == "quorum-rtt"
    ex = rs["exemplars"][0]
    names = [ev["name"] for e in ex["path"] for ev in e.get("events", ())]
    assert any(n.startswith("chaos.") for n in names)


# ------------------------------------------------------------- TimedQueue


def test_timed_queue_bounds_and_drop_reasons():
    reg = Registry()
    clk = [0.0]
    q = TimedQueue("test-q", maxlen=2, clock=lambda: clk[0], registry=reg)
    assert q.offer("a") and q.offer("b")
    assert not q.offer("c")  # full
    assert q.dropped("full") == 1
    assert reg.value("dds_queue_dropped_total", queue="test-q",
                     reason="full") == 1
    q.drop(3, reason="no_pool")
    assert q.dropped("no_pool") == 3 and q.dropped() == 4
    assert q.offer_many(["d", "e"]) == 0  # still full, both rejected
    assert q.dropped("full") == 3
    clk[0] = 0.25
    entries = q.drain_entries()
    assert [i for _, i in entries] == ["a", "b"]
    assert all(w == pytest.approx(0.25) for w, _ in entries)
    assert q.depth() == 0 and q.drain() == []
    st = q.stats()
    assert st["offered"] == 2 and st["drained"] == 2
    assert st["dropped"] == {"full": 3, "no_pool": 3}


def test_timed_queue_age_clear_and_gauges():
    reg = Registry()
    clk = [10.0]
    q = TimedQueue("age-q", clock=lambda: clk[0], registry=reg)
    q.offer("x")
    clk[0] = 10.5
    assert q.oldest_age() == pytest.approx(0.5)
    q.export_gauges(reg)
    text = reg.render()
    assert 'dds_queue_depth{queue="age-q"} 1' in text
    assert 'dds_queue_oldest_age_seconds{queue="age-q"} 0.5' in text
    assert q.clear(reason="invalidated") == 1
    assert q.dropped("invalidated") == 1
    assert q.clear() == 0


def test_timed_queue_drain_records_queue_wait_span():
    tracer.reset()
    clk = [0.0]
    q = TimedQueue("span-q", clock=lambda: clk[0], registry=Registry())
    q.offer("x")
    clk[0] = 0.1
    q.drain()
    waits = tracer.events("ingest.queue_wait")
    assert len(waits) == 1
    assert waits[0].dur_ms == pytest.approx(100.0, abs=0.5)
    assert waits[0].meta["queue"] == "span-q"


# ------------------------------------------------- kprof compile split


def test_kprof_splits_cold_compile_from_warm_dispatch():
    from dds_tpu.obs import kprof

    kprof.reset()
    tracer.reset()
    kprof.cache_event("splitk", hit=False)   # builder cache miss -> cold
    kprof.profiled("splitk", lambda: 3)
    kprof.cache_event("splitk", hit=True)
    kprof.profiled("splitk", lambda: 3)      # warm
    names = [e.name for e in tracer.events() if e.name.startswith("kernel.")]
    assert names.count("kernel.splitk.compile") == 1
    assert names.count("kernel.splitk.dispatch") == 1
    assert names.count("kernel.splitk.execute") == 2
    summary = kprof.kernel_summary()
    assert summary["compile_ms"] >= 0 and "compile_ms" in summary


def test_sentry_collect_includes_compile_phase():
    from dds_tpu.obs import sentry
    from dds_tpu.utils.trace import Tracer

    t = Tracer()
    t.record("kernel.splitk.compile", 5.0, k=4)
    t.record("kernel.splitk.dispatch", 1.0, k=4)
    t.record("kernel.splitk.execute", 2.0, k=4)
    stats = sentry.collect(t)
    (key,) = [k for k in stats if "splitk" in k]
    assert set(stats[key]) == {"compile", "dispatch", "execute"}
    # round-trips through the baseline schema
    assert sentry.compare({key: stats[key]}, {key: stats[key]}) == []


# --------------------------------------------- Panopticon fleet rollup


class _StubNet:
    """The TcpNet sliver FleetCollector touches: addr composition,
    endpoint registry, fire-and-forget send."""

    def __init__(self, advertised="127.0.0.1:70"):
        self.advertised = advertised
        self.handlers = {}
        self.sent = []

    def local_addr(self, name):
        return f"{self.advertised}/{name}"

    def register(self, addr, handler):
        self.handlers[addr.rsplit("/", 1)[-1]] = handler

    def unregister(self, addr):
        self.handlers.pop(addr.rsplit("/", 1)[-1], None)

    def send(self, src, dest, msg):
        self.sent.append((src, dest, msg))


def _pipe_text(route, stage, p95, wall=50.0, cov=0.97):
    return "\n".join([
        f'dds_pipe_wall_p95_ms{{route="{route}"}} {wall}',
        f'dds_pipe_coverage{{route="{route}"}} {cov}',
        f'dds_pipe_stage_p95_ms{{route="{route}",stage="{stage}"}} {p95}',
        "",
    ])


def test_fleet_profile_rolls_up_max_across_hosts():
    from dds_tpu.obs.panopticon import FleetCollector

    reg = Registry()
    reg.set("dds_pipe_wall_p95_ms", 50.0, route="http.POST.PutSet")
    reg.set("dds_pipe_coverage", 0.99, route="http.POST.PutSet")
    reg.set("dds_pipe_stage_p95_ms", 12.0, route="http.POST.PutSet",
            stage="quorum-rtt")
    col = FleetCollector(
        _StubNet(), secret=b"s", host="proxy-0", registry=reg,
        watchtower=SimpleNamespace(on_record=lambda r: None))
    col._sources["group-1"] = {
        "role": "group", "shard": "s0", "ts": 0.0, "region": "",
        "mono": time.monotonic(), "seq": 1, "slo": {}, "dropped": 0,
        "metrics_text": _pipe_text("http.POST.PutSet", "replica-apply", 30.0),
    }
    fp = col.fleet_profile()
    route = fp["fleet"]["routes"]["http.POST.PutSet"]
    assert route["wall_p95_ms"] == 50.0
    assert route["coverage_min"] == 0.97
    assert route["stages"]["replica-apply"] == {
        "p95_ms": 30.0, "host": "group-1"}
    assert route["top_stage"]["stage"] == "replica-apply"
    assert fp["fleet"]["top"] == {
        "route": "http.POST.PutSet", "stage": "replica-apply",
        "p95_ms": 30.0, "host": "group-1"}
    assert "proxy-0" in fp["hosts"] and "group-1" in fp["hosts"]


def test_collector_replay_feeds_profiler_stitched_tree():
    from dds_tpu.obs.panopticon import FleetCollector

    col = FleetCollector(
        _StubNet(), secret=b"s", host="proxy-0", registry=Registry(),
        watchtower=SimpleNamespace(on_record=lambda r: None),
        stitch_window=0.0)
    cs = Chronoscope(registry=Registry(), slow_ms=1e9)
    col.profiler = cs
    col._buffer(S("abd.write", 0.01, 0.09, "c", "r", tid="tz"), local=True)
    col._buffer(S("replica.handle", 0.02, 0.06, "h", "c", tid="tz"),
                local=False)
    col._buffer(S("http.POST.PutSet", 0.0, 0.1, "r", tid="tz"), local=True)
    col._replay_due()
    prof = cs.profile()
    assert prof["routes"]["http.POST.PutSet"]["count"] == 1
    assert prof["routes"]["http.POST.PutSet"]["stages"]["replica-apply"]


# ------------------------------------------- sentry `pipe profile` contract


def test_sentry_validates_pipe_profile_records(tmp_path):
    from benchmarks.sentry import _check_pipe_records

    good = {
        "metric": "pipe profile", "value": 43.1, "unit": "ms",
        "vs_baseline": 0.97,
        "detail": {
            "rate": 60.0, "duration": 2.0, "processes": 3,
            "open_loop": True, "route": "http.POST.PutSet",
            "wall_p95_ms": 43.1, "coverage": 0.968,
            "top_stage": "quorum-rtt",
            "stages": {"quorum-rtt": 21.0, "response": 5.2},
            "fleet_top_stage": "quorum-rtt", "agree": True,
            "traces_profiled": 110, "on_good": 105, "off_good": 107,
            "overhead_pct": 1.87,
        },
    }
    bench = tmp_path / "benchmarks"
    bench.mkdir()
    (bench / "results_quick.json").write_text(json.dumps([good]))
    assert _check_pipe_records(str(tmp_path)) == {"rows": 1}
    for mutate in (
        {"value": 0},                                        # no wall time
        {"detail": dict(good["detail"], route="")},
        {"detail": dict(good["detail"], coverage=1.5)},      # not a fraction
        {"detail": dict(good["detail"], top_stage="warp")},  # off-taxonomy
        {"detail": dict(good["detail"], stages={})},         # nothing named
        {"detail": dict(good["detail"], stages={"quorum-rtt": -1})},
        {"detail": dict(good["detail"], agree="yes")},
        {"detail": dict(good["detail"], processes=1)},       # not a fleet
        {"detail": dict(good["detail"], open_loop=False)},
        {"detail": dict(good["detail"], overhead_pct="2%")},
    ):
        (bench / "results_quick.json").write_text(
            json.dumps([dict(good, **mutate)]))
        with pytest.raises(ValueError):
            _check_pipe_records(str(tmp_path))
    (bench / "results_quick.json").write_text(json.dumps([{"metric": "sweep"}]))
    assert _check_pipe_records(str(tmp_path)) == {"rows": 0}
