"""Randomized linearizability / atomicity properties of the BFT-ABD core.

The reference verifies its protocol only operationally (SURVEY.md §4);
these are the property tests it never had. ABD with the read write-back
phase implements an *atomic* (linearizable) multi-writer register: we
record operation intervals in real time and check the two violations a
register can exhibit:

- a read returning a value whose write started after the read ended
  (reading from the future), and
- new/old inversion: once a read returns a write W2 that is real-time
  ordered after W1, no later read may return W1 again.

Also exercises Trudy mid-workload: crashes and compromises within the
f=2 budget must not break the properties or liveness.

The chaos suite at the bottom re-runs the same history checker under
seeded ChaosNet fault schedules (partition during writes, delay storms
during proactive recovery, duplicate/reorder during tag reads, lossy and
corrupting links, mixed Nemesis attacks): linearizability must hold
THROUGH the faults and the cluster must converge after heal. Schedules
are capped by short intervals (ms-scale delays, sub-second partitions)
and per-op deadline budgets, so the whole suite stays inside the tier-1
time budget.
"""

import asyncio
import itertools
import random
import time

import pytest

from dds_tpu.core.chaos import ChaosNet, LinkFaults
from dds_tpu.core.transport import InMemoryNet
from dds_tpu.malicious.trudy import Nemesis, Trudy
from dds_tpu.utils.retry import Deadline, RetryPolicy, retry, retry_deadline
from tests.test_core import Cluster, run


KEY = "LINREG"


class Recorder:
    def __init__(self):
        self.ops = []

    def record(self, kind, value, start, end):
        self.ops.append({"kind": kind, "value": value, "start": start, "end": end})


def check_atomic_register(ops):
    """Assert the recorded history is consistent with an atomic register.

    Conservative (sound, incomplete) checks that need no search:
    1. every read's value was None or written by some write that STARTED
       before the read ENDED;
    2. if write W1 ENDED before write W2 STARTED (real-time ordered) then
       after any read returns W2's value, no read that STARTS after that
       read ENDS may return W1's value (new/old inversion).
    """
    writes = {o["value"]: o for o in ops if o["kind"] == "write"}
    reads = sorted(
        (o for o in ops if o["kind"] == "read"), key=lambda o: o["start"]
    )
    for r in reads:
        if r["value"] is None:
            continue
        w = writes.get(r["value"])
        assert w is not None, f"read returned a never-written value {r['value']}"
        assert w["start"] <= r["end"], "read returned a value from the future"

    for r1, r2 in itertools.combinations(reads, 2):
        # reads sorted by start; require real-time ordering r1 before r2
        if r1["end"] > r2["start"]:
            continue
        if r1["value"] is None or r2["value"] is None:
            continue
        w1, w2 = writes[r1["value"]], writes[r2["value"]]
        if w2["end"] < w1["start"]:
            raise AssertionError(
                f"new/old inversion: read@{r1['start']:.4f} saw {r1['value']} "
                f"but later read@{r2['start']:.4f} saw older {r2['value']}"
            )


async def _writer(cluster, rec, wid, n_writes, rng):
    """Writes with the proxy's retry discipline (the reference wraps every
    writeSet in FutureRetry — crashed coordinators are retried elsewhere
    while suspicion accrues, `DDSRestServer.scala:178`)."""
    for i in range(n_writes):
        value = [f"w{wid}-{i}"]
        t0 = time.monotonic()
        await retry(lambda: cluster.client.write_set(KEY, value), 0.01, 5)
        rec.record("write", f"w{wid}-{i}", t0, time.monotonic())
        await asyncio.sleep(rng.uniform(0, 0.002))


async def _reader(cluster, rec, n_reads, rng):
    for _ in range(n_reads):
        t0 = time.monotonic()
        got = await retry(lambda: cluster.client.fetch_set(KEY), 0.01, 5)
        rec.record("read", got[0] if got else None, t0, time.monotonic())
        await asyncio.sleep(rng.uniform(0, 0.002))


def test_concurrent_writers_atomic_register():
    async def go():
        rng = random.Random(11)
        c = Cluster()
        rec = Recorder()
        await asyncio.gather(
            _writer(c, rec, 0, 6, rng),
            _writer(c, rec, 1, 6, rng),
            _writer(c, rec, 2, 6, rng),
            _reader(c, rec, 12, rng),
            _reader(c, rec, 12, rng),
        )
        check_atomic_register(rec.ops)
        # convergence: a final read agrees with a quorum of replicas
        final = await c.client.fetch_set(KEY)
        await c.net.quiesce()
        holders = [
            r for r in c.replicas.values()
            if r.repository.get(KEY, (None, None))[1] == final
        ]
        assert len(holders) >= 5

    run(go())


def test_atomicity_checker_catches_inversion():
    """The checker itself must reject a known-bad history."""
    bad = [
        {"kind": "write", "value": "old", "start": 0.0, "end": 0.1},
        {"kind": "write", "value": "new", "start": 0.2, "end": 0.3},
        {"kind": "read", "value": "new", "start": 0.4, "end": 0.5},
        {"kind": "read", "value": "old", "start": 0.6, "end": 0.7},
    ]
    try:
        check_atomic_register(bad)
    except AssertionError:
        return
    raise AssertionError("checker accepted a new/old inversion")


def test_crash_faults_mid_workload():
    """Trudy crashes f=2 replicas between writes; properties + liveness hold."""

    async def go():
        rng = random.Random(23)
        c = Cluster()
        c.client.cfg.request_timeout = 0.2  # fast retry on crashed coordinators
        rec = Recorder()
        trudy = Trudy(c.net, c.active, max_faults=2, rng=random.Random(5))

        async def attacker():
            await asyncio.sleep(0.01)
            trudy.trigger("crash")

        await asyncio.gather(
            _writer(c, rec, 0, 8, rng),
            _reader(c, rec, 16, rng),
            attacker(),
        )
        check_atomic_register(rec.ops)
        # single writer: its last write is the register's final value
        assert await c.client.fetch_set(KEY) == ["w0-7"]

    run(go())


def test_byzantine_faults_mid_workload():
    """Compromised replicas (valid MAC keys, garbage behavior) within f=2
    cannot corrupt reads: every read still satisfies the register checks
    and returns only genuinely-written values."""

    async def go():
        rng = random.Random(31)
        c = Cluster()
        rec = Recorder()
        trudy = Trudy(c.net, c.active, max_faults=2, rng=random.Random(9))

        async def attacker():
            await asyncio.sleep(0.005)
            trudy.trigger("byzantine")

        await asyncio.gather(
            _writer(c, rec, 0, 6, rng),
            _writer(c, rec, 1, 6, rng),
            _reader(c, rec, 14, rng),
            attacker(),
        )
        check_atomic_register(rec.ops)

    run(go())


# ---------------------------------------------------------------------------
# chaos suite: the SAME atomic-register checker under seeded fault schedules
# ---------------------------------------------------------------------------

# fast, deadline-governed retry for chaos workloads: ops keep retrying
# through a fault window and must complete once it heals, within budget
_CHAOS_POLICY = RetryPolicy(base=0.01, multiplier=2.0, max_delay=0.08)


def chaos_cluster(seed, request_timeout=0.25, **kw):
    net = ChaosNet(InMemoryNet(), seed=seed)
    c = Cluster(net=net, **kw)
    c.client.cfg.request_timeout = request_timeout
    c.client.cfg.breaker_reset = 0.15
    return c, net


async def _chaos_writer(cluster, rec, wid, n_writes, seed, budget=15.0):
    rng = random.Random(seed)
    for i in range(n_writes):
        value = [f"w{wid}-{i}"]
        t0 = time.monotonic()
        dl = Deadline(budget)
        await retry_deadline(
            lambda: cluster.client.write_set(KEY, value, deadline=dl),
            dl, _CHAOS_POLICY, rng=rng,
        )
        rec.record("write", f"w{wid}-{i}", t0, time.monotonic())
        await asyncio.sleep(rng.uniform(0, 0.002))


async def _chaos_reader(cluster, rec, n_reads, seed, budget=15.0):
    rng = random.Random(seed)
    for _ in range(n_reads):
        t0 = time.monotonic()
        dl = Deadline(budget)
        got = await retry_deadline(
            lambda: cluster.client.fetch_set(KEY, deadline=dl),
            dl, _CHAOS_POLICY, rng=rng,
        )
        rec.record("read", got[0] if got else None, t0, time.monotonic())
        await asyncio.sleep(rng.uniform(0, 0.002))


async def _converged_holders(c, expect):
    await c.net.quiesce()
    return [
        r for r in c.replicas.values()
        if r.repository.get(KEY, (None, None))[1] == expect
    ]


@pytest.mark.chaos
def test_chaos_minority_partition_during_writes_linearizable():
    """Schedule 1: a minority partition (2 of 7) opens mid-workload and
    heals on a timer; the remaining quorum keeps serving, every recorded
    history linearizes, and a quorum converges on the final value."""

    async def go():
        c, net = chaos_cluster(seed=101)
        rec = Recorder()

        async def attacker():
            await asyncio.sleep(0.01)
            net.partition(["replica-5", "replica-6"], duration=0.15)

        await asyncio.gather(
            _chaos_writer(c, rec, 0, 5, seed=1),
            _chaos_writer(c, rec, 1, 5, seed=2),
            _chaos_reader(c, rec, 10, seed=3),
            attacker(),
        )
        check_atomic_register(rec.ops)
        final = await c.client.fetch_set(KEY)
        assert len(await _converged_holders(c, final)) >= 5

    run(go())


@pytest.mark.chaos
def test_chaos_quorum_breaking_partition_stalls_then_heals():
    """Schedule 2: partitioning 3 of 7 leaves 4 < quorum — writes STALL
    (no wrong answers) until the timed heal, then complete within their
    deadline budgets; the history stays linearizable throughout."""

    async def go():
        c, net = chaos_cluster(seed=202, request_timeout=0.15)
        rec = Recorder()

        async def attacker():
            await asyncio.sleep(0.01)
            net.partition(
                ["replica-0", "replica-1", "replica-2"], duration=0.3
            )

        await asyncio.gather(
            _chaos_writer(c, rec, 0, 4, seed=4),
            _chaos_reader(c, rec, 6, seed=5),
            attacker(),
        )
        check_atomic_register(rec.ops)
        # single writer: its last write is the register's final value
        assert await c.client.fetch_set(KEY) == ["w0-3"]

    run(go())


@pytest.mark.chaos
def test_chaos_delay_storm_during_proactive_recovery():
    """Schedule 3: jittered delays on EVERY link while the proactive
    recovery timer swaps replicas mid-workload. Linearizability holds,
    and after heal the supervisor converges back to full membership.

    Event-driven (deflaked): the membership assertion waits on the
    supervisor's recovery-complete hook instead of racing stop() against
    an in-flight swap — cancelling recover() mid-swap left a spare
    promoted with the offender not yet demoted (8 active / 1 sentinent),
    the pre-existing 8/10 isolation failure. stop() itself is now
    graceful (awaits the shielded in-flight recovery), and the explicit
    wait asserts the hook resolves within the recovery timeouts."""

    async def go():
        c, net = chaos_cluster(seed=303, proactive=True)
        net.default_faults = LinkFaults(delay=0.002, jitter=0.008)
        c.supervisor.start()
        rec = Recorder()
        await asyncio.gather(
            _chaos_writer(c, rec, 0, 6, seed=6),
            _chaos_reader(c, rec, 10, seed=7),
        )
        net.heal_all()
        assert await c.supervisor.wait_recovery_idle(10.0), (
            "recovery never quiesced after heal"
        )
        await c.supervisor.stop()
        await net.quiesce()
        check_atomic_register(rec.ops)
        # supervisor converged after heal: membership sizes intact
        active = [a for a, _ in c.supervisor.active]
        assert len(active) == len(set(active)) == 7
        assert len(c.supervisor.sentinent) == 2

    run(go())


@pytest.mark.chaos
def test_chaos_duplicate_reorder_during_tag_reads():
    """Schedule 4: duplication + reordering on the proxy<->replica links
    while writes interleave with batched tag reads. Duplicated replies
    must not stuff quorums (votes key by sender), reordered replies must
    not corrupt correlation, and the final tag round agrees with the last
    completed write."""

    async def go():
        c, net = chaos_cluster(seed=404)
        for i in range(7):
            net.set_pair(
                "proxy-0", f"replica-{i}",
                LinkFaults(duplicate=0.3, reorder=0.3),
            )
        rec = Recorder()
        tag_rounds = {"n": 0}

        async def tag_reader():
            rng = random.Random(8)
            for _ in range(8):
                dl = Deadline(15.0)
                tags = await retry_deadline(
                    lambda: c.client.read_tags([KEY], deadline=dl),
                    dl, _CHAOS_POLICY, rng=rng,
                )
                assert len(tags) == 1
                tag_rounds["n"] += 1
                await asyncio.sleep(rng.uniform(0, 0.003))

        await asyncio.gather(
            _chaos_writer(c, rec, 0, 6, seed=9),
            _chaos_reader(c, rec, 8, seed=10),
            tag_reader(),
        )
        check_atomic_register(rec.ops)
        assert tag_rounds["n"] == 8
        await net.quiesce()
        # the quorum-max tag now equals the last completed write's tag
        value, tag = await c.client.fetch_set_tagged(KEY)
        assert value == ["w0-5"]
        assert (await c.client.read_tags([KEY])) == [tag]

    run(go())


@pytest.mark.chaos
def test_chaos_lossy_corrupting_links_linearizable():
    """Schedule 5: 5% drop + 3% payload corruption + jitter on every link.
    Corrupted protocol messages must die at the HMAC/codec layers (never
    surface as values), lost messages are absorbed by retries, and the
    history still linearizes."""

    async def go():
        c, net = chaos_cluster(seed=505)
        net.default_faults = LinkFaults(drop=0.05, corrupt=0.03, jitter=0.003)
        rec = Recorder()
        await asyncio.gather(
            _chaos_writer(c, rec, 0, 5, seed=11),
            _chaos_writer(c, rec, 1, 5, seed=12),
            _chaos_reader(c, rec, 8, seed=13),
        )
        check_atomic_register(rec.ops)
        # every read surfaced a genuinely-written value (checker asserts
        # this) and the workload completed despite the loss schedule
        assert sum(1 for o in rec.ops if o["kind"] == "write") == 10
        net.heal_all()
        final = await c.client.fetch_set(KEY)
        assert len(await _converged_holders(c, final)) >= 5

    run(go())


@pytest.mark.chaos
def test_chaos_nemesis_mixed_attack_schedule():
    """Schedule 6: Nemesis drives a mixed attack — one replica compromised
    (byzantine), one partitioned, junk floods at a third — all within the
    f=2 budget, healed mid-workload. Linearizability and liveness hold."""

    async def go():
        c, net = chaos_cluster(seed=606)
        rec = Recorder()
        nem = Nemesis(net, c.active, max_faults=1, rng=random.Random(42),
                      flood_messages=15)

        async def attacker():
            await asyncio.sleep(0.005)
            byz = nem.trigger("byzantine")
            # partition a DIFFERENT replica so total faults stay at f=2
            nem.replicas = [a for a in c.active if a not in byz]
            cut = nem.trigger("partition")
            nem.replicas = [a for a in c.active if a not in byz + cut]
            nem.trigger("flood")
            await asyncio.sleep(0.12)
            nem.trigger("heal")

        await asyncio.gather(
            _chaos_writer(c, rec, 0, 5, seed=14),
            _chaos_reader(c, rec, 8, seed=15),
            attacker(),
        )
        check_atomic_register(rec.ops)
        assert await c.client.fetch_set(KEY) == ["w0-4"]

    run(go())


def test_coalesced_sumalls_see_old_or_new_never_mixed_garbage():
    """Aggregate linearizability through the COALESCED fold path: while a
    stored key's value is rewritten (v_old -> v_new), a storm of
    concurrent SumAlls — small enough that they share coalesced device
    dispatches — must each decrypt to sum_old or sum_new, never anything
    else. Coalescing shares only the MATH of concurrent folds; each
    request's operand snapshot still comes from its own quorum-validated
    read, which this test pins down. A spy asserts the coalesced
    dispatch genuinely ran (the claim is enforceable, not incidental)."""
    import json

    from dds_tpu.models import HEKeys
    from dds_tpu.models.backend import TpuBackend
    from tests.test_rest import call, rest_stack

    keys = HEKeys.generate(paillier_bits=512, rsa_bits=512)
    pk = keys.psse.public

    async def go():
        async with rest_stack(n=4, quorum=3) as (server, _, _):
            be = TpuBackend(pallas=False, min_device_batch=8)
            calls = {"many": 0}
            orig_many = be.modmul_fold_many
            be.modmul_fold_many = lambda folds, mod: (
                calls.__setitem__("many", calls["many"] + 1)
                or orig_many(folds, mod)
            )
            server.backend = be
            base_vals = [10, 20, 30, 40]
            row_keys = []
            for v in base_vals:
                st, body = await call(
                    server, "POST", "/PutSet", {"contents": [str(pk.encrypt(v))]}
                )
                assert st == 200
                row_keys.append(body.decode())

            old_total = sum(base_vals)
            new_last = 999
            new_total = old_total - base_vals[-1] + new_last
            target = f"/SumAll?position=0&nsqr={pk.nsquare}"

            async def storm(n):
                rs = await asyncio.gather(*(call(server, "GET", target)
                                            for _ in range(n)))
                out = []
                for st, data in rs:
                    assert st == 200
                    out.append(keys.psse.decrypt(int(json.loads(data)["result"])))
                return out

            async def rewrite():
                st, _ = await call(
                    server, "PUT",
                    f"/WriteElement/{row_keys[-1]}?position=0",
                    {"value": str(pk.encrypt(new_last))},
                )
                assert st == 200

            sums, _ = await asyncio.gather(storm(12), rewrite())
            allowed = {old_total, new_total}
            assert set(sums) <= allowed, (sums, allowed)
            assert calls["many"] >= 1  # the coalesced path really ran
            # afterwards every aggregate sees the new value
            settled = await storm(4)
            assert set(settled) == {new_total}

    asyncio.run(go())
