"""Lodestone resident-plane tests (dds_tpu/resident).

Covers the ISSUE 9 acceptance surface: per-group pools (content
addressing, doubling, reset-epoch semantics), the fused single-dispatch
sharded fold (bit-for-bit vs the host reference fold, S=4 vs S=1 over
IDENTICAL ciphertexts, exactly one kernel.resident_fold dispatch span
per warm aggregate), write-path incremental ingest (a warm fleet's first
post-write aggregate pays zero ingest; ingest racing an aggregate over
the same values stays bit-for-bit and deadlock-free), the concurrency
races around capacity resets and `_idx_memo` epoch invalidation, the
direct-fallback metric accounting fix, the /metrics + /health surface,
and the sentry `resident fold` record contract.
"""

import asyncio
import json
import random
import threading

import pytest

from dds_tpu.http.miniserver import http_request
from dds_tpu.http.server import DDSRestServer, ProxyConfig
from dds_tpu.models import HEKeys
from dds_tpu.obs.metrics import metrics
from dds_tpu.resident import ResidentPlane, ResidentPool
from dds_tpu.utils.config import ResidentConfig
from dds_tpu.utils.trace import tracer

pytestmark = pytest.mark.resident

rng = random.Random(0x10DE)
KEYS = HEKeys.generate(paillier_bits=512, rsa_bits=512)
MODULUS = rng.getrandbits(256) | (1 << 255) | 1


def pyfold(cs, n=MODULUS):
    acc = 1
    for c in cs:
        acc = acc * c % n
    return acc


def _metric(name, **labels):
    return metrics.value(name, **labels) or 0


# ------------------------------------------------------------------- pools


def test_direct_fallback_accounts_direct_not_resident():
    """Satellite fix: an aggregate wider than max_rows host-marshals every
    limb for a direct fold — it must report outcome="direct", not claim
    the operands were resident."""
    pool = ResidentPool(MODULUS, initial_rows=4, max_rows=8, gid="sX")
    cs = [rng.randrange(1, MODULUS) for _ in range(12)]  # > max_rows
    before = {
        o: _metric("dds_cipher_store_total", outcome=o)
        for o in ("resident", "ingested", "direct")
    }
    assert pool.fold(cs) == pyfold(cs)
    assert _metric("dds_cipher_store_total", outcome="direct") \
        == before["direct"] + len(cs)
    assert _metric("dds_cipher_store_total", outcome="resident") \
        == before["resident"]
    assert _metric("dds_cipher_store_total", outcome="ingested") \
        == before["ingested"]
    assert pool.hit_ratio() == 0.0


def test_epoch_invalidates_idx_memo_across_reset():
    """A capacity reset must invalidate row-index memos minted against
    the old placement: the SAME operand-list object folds correctly after
    rows were evicted and re-placed."""
    pool = ResidentPool(MODULUS, initial_rows=4, max_rows=8)
    cs = [rng.randrange(1, MODULUS) for _ in range(4)]
    assert pool.fold(cs) == pyfold(cs)
    assert pool._idx_memo is not None and pool._idx_memo[0] is cs
    epoch0 = pool.epoch
    # overflow with fresh values: forces the reset path, bumping the epoch
    flood = [rng.randrange(1, MODULUS) for _ in range(7)]
    assert pool.fold(flood) == pyfold(flood)
    assert pool.epoch > epoch0 and pool.resets >= 1
    # same list object again: the stale memo must NOT serve old indices
    assert pool.fold(cs) == pyfold(cs)
    assert pool._idx_memo[1] == pool.epoch


def test_capacity_reset_racing_concurrent_folds():
    """Folds on worker threads racing overflow-induced resets must always
    return the correct product (and never deadlock)."""
    pool = ResidentPool(MODULUS, initial_rows=4, max_rows=16)
    stable = [rng.randrange(1, MODULUS) for _ in range(5)]
    expect = pyfold(stable)
    errors = []

    def folder():
        for _ in range(12):
            try:
                if pool.fold(stable) != expect:
                    errors.append("wrong fold result")
            except Exception as e:  # pragma: no cover - failure surface
                errors.append(repr(e))

    def flooder(seed):
        r = random.Random(seed)
        for _ in range(12):
            flood = [r.randrange(1, MODULUS) for _ in range(13)]
            try:
                if pool.fold(flood) != pyfold(flood):
                    errors.append("wrong flood result")
            except Exception as e:  # pragma: no cover
                errors.append(repr(e))

    threads = [threading.Thread(target=folder) for _ in range(2)] + [
        threading.Thread(target=flooder, args=(i,)) for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "fold/reset race deadlocked"
    assert not errors, errors
    assert pool.resets >= 1  # the race actually exercised resets


def test_write_ingest_racing_aggregate_bit_for_bit():
    """Write-path ingest racing a fused fold over the same ciphertexts:
    content addressing means both sides converge on identical rows —
    results stay bit-for-bit the host fold, nothing deadlocks."""
    plane = ResidentPlane(initial_rows=8, max_rows=256)
    parts = [
        (f"s{i}", [rng.randrange(1, MODULUS) for _ in range(6)])
        for i in range(3)
    ]
    allops = [c for _, ops in parts for c in ops]
    expect = pyfold(allops)
    plane.fold_groups(parts, MODULUS)  # establish the pools
    errors = []

    def writer():
        for _ in range(10):
            for gid, ops in parts:
                assert plane.note_write(gid, list(ops)) >= 0
            plane.ingest_pending()

    def folder():
        for _ in range(10):
            try:
                if plane.fold_groups(parts, MODULUS) != expect:
                    errors.append("fused fold diverged under ingest race")
            except Exception as e:  # pragma: no cover
                errors.append(repr(e))

    threads = [threading.Thread(target=writer),
               threading.Thread(target=folder)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "ingest/fold race deadlocked"
    assert not errors, errors


def test_group_sharding_single_device_is_plain_buffer():
    from dds_tpu.parallel.mesh import group_sharding, make_mesh

    assert group_sharding(None, 0) is None
    assert group_sharding(make_mesh(1), 2) is None  # single device = today


# --------------------------------------------------- fused sharded aggregates


def _rest_constellation(S, resident=True):
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.shard import build_constellation

    net = InMemoryNet()
    const = build_constellation(net, shard_count=S, vnodes_per_group=8,
                                seed=3, n_active=4, n_sentinent=0, quorum=3)
    cfg = ProxyConfig(
        port=0, crypto_backend="cpu",
        resident=(ResidentConfig(enabled=True, min_fold=1)
                  if resident else None),
    )
    server = DDSRestServer(const.router, cfg)
    return server, const


def test_warm_sharded_aggregate_bit_for_bit_and_single_dispatch():
    """Acceptance (ISSUE 9): warm sharded SumAll/MultAll over resident
    pools is bit-for-bit the host reference fold (S=4 vs S=1 over
    IDENTICAL ciphertexts) and dispatches exactly ONE fused fold per
    aggregate (kernel.resident_fold spans), ingesting nothing."""
    pk = KEYS.psse.public
    rsa_n = KEYS.mse.n
    vals = [7, 21, 301, 44, 5, 600, 13, 99]
    rows = [[str(pk.encrypt(v)), str(v + 2)] for v in vals]  # pos 1: mod-n ints
    expect_sum = pyfold([int(r[0]) for r in rows], pk.nsquare)
    expect_mult = pyfold([int(r[1]) for r in rows], rsa_n)

    async def serve(S):
        server, const = _rest_constellation(S)
        await server.start()
        try:
            for row in rows:
                st, _ = await http_request(
                    "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                    json.dumps({"contents": row}).encode(), timeout=10.0,
                )
                assert st == 200
            if S > 1:  # the sample must genuinely span shards
                assert len(server.abd.partition_keys(
                    sorted(server.stored_keys))) > 1
            out = {}
            for route, mod in (("SumAll", f"nsqr={pk.nsquare}"),
                               ("MultAll", f"pubkey={rsa_n}")):
                # cold pass ingests; warm pass must gather resident rows
                # in ONE dispatch
                pos = 0 if route == "SumAll" else 1
                target = f"/{route}?position={pos}&{mod}"
                st, _ = await http_request(
                    "127.0.0.1", server.cfg.port, "GET", target, timeout=30.0)
                assert st == 200
                ingested = _metric("dds_cipher_store_total",
                                   outcome="ingested")
                tracer.reset()
                st, body = await http_request(
                    "127.0.0.1", server.cfg.port, "GET", target, timeout=30.0)
                assert st == 200
                spans = tracer.summary()
                assert spans.get("kernel.resident_fold.dispatch",
                                 {}).get("count") == 1, spans
                assert _metric("dds_cipher_store_total",
                               outcome="ingested") == ingested
                out[route] = json.loads(body)["result"]
            return out
        finally:
            await server.stop()
            await const.stop()

    async def go():
        single = await serve(1)
        sharded = await serve(4)
        assert sharded == single  # bit-for-bit across shard counts
        assert int(single["SumAll"]) == expect_sum  # == host reference fold
        assert int(single["MultAll"]) == expect_mult
        assert KEYS.psse.decrypt(int(single["SumAll"])) == sum(vals)

    asyncio.run(go())


def test_write_path_ingest_warms_first_post_write_aggregate():
    """A committed write ingests into the established pools off the
    request path: the FIRST post-write aggregate finds every row resident
    (zero fold-path ingest)."""
    pk = KEYS.psse.public
    vals = [31, 17, 255]

    async def go():
        server, const = _rest_constellation(4)
        await server.start()
        try:
            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            for v in vals:
                st, _ = await http_request(
                    "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                    json.dumps({"contents": [str(pk.encrypt(v))]}).encode(),
                    timeout=10.0,
                )
                assert st == 200
            st, _ = await http_request("127.0.0.1", server.cfg.port, "GET",
                                       target, timeout=30.0)
            assert st == 200  # pools established for this modulus
            # the write: ingest must happen NOW, not at the next
            # aggregate. Only groups that already own an operand have a
            # pool, so pick an encryption whose (content-addressed) key
            # lands in a pooled group — blinding re-randomizes the
            # ciphertext, hence the key, every attempt.
            from dds_tpu.utils import sigs

            pooled = {p["shard"]
                      for p in server._resident.stats()["pools"]}
            extra = 777
            while True:
                row = [str(pk.encrypt(extra))]
                if server.abd.owner(sigs.key_from_set(row)) in pooled:
                    break
            st, _ = await http_request(
                "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                json.dumps({"contents": row}).encode(), timeout=10.0,
            )
            assert st == 200
            assert server._ingest_task is not None
            await server._ingest_task  # event-driven: the debounced drain
            assert server._resident.pending_ingest() == 0
            rows_now = sum(p["rows"]
                           for p in server._resident.stats()["pools"])
            assert rows_now == len(vals) + 1  # the new row already landed
            fold_ingest = _metric("dds_resident_ingest_total", path="fold")
            st, body = await http_request("127.0.0.1", server.cfg.port,
                                          "GET", target, timeout=30.0)
            assert st == 200
            # zero fold-path ingest on the first post-write aggregate
            assert _metric("dds_resident_ingest_total",
                           path="fold") == fold_ingest
            assert KEYS.psse.decrypt(int(json.loads(body)["result"])) \
                == sum(vals) + extra
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_metrics_and_health_surface():
    pk = KEYS.psse.public

    async def go():
        server, const = _rest_constellation(2)
        await server.start()
        try:
            for v in (5, 6, 7, 8):
                await http_request(
                    "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                    json.dumps({"contents": [str(pk.encrypt(v))]}).encode(),
                    timeout=10.0,
                )
            await http_request(
                "127.0.0.1", server.cfg.port, "GET",
                f"/SumAll?position=0&nsqr={pk.nsquare}", timeout=30.0)
            st, body = await http_request("127.0.0.1", server.cfg.port,
                                          "GET", "/metrics", timeout=10.0)
            assert st == 200
            text = body.decode()
            for fam in ("dds_resident_rows", "dds_resident_bytes",
                        "dds_resident_hit_ratio"):
                assert f'{fam}{{shard="s' in text, fam
            st, body = await http_request("127.0.0.1", server.cfg.port,
                                          "GET", "/health", timeout=10.0)
            health = json.loads(body)
            assert "resident" in health
            assert health["resident"]["pools"], health["resident"]
            assert all(p["bytes"] == p["capacity"] * 64 * 4  # L=64 @ 1024b
                       for p in health["resident"]["pools"])
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


# ------------------------------------------------------------- prism + bench


def test_fold_weighted_resident_rows_bit_for_bit():
    """fold_weighted fed pre-gathered resident rows must equal the
    marshaling path (same kernel, same result)."""
    from dds_tpu.ops.foldmany import fold_weighted

    plane = ResidentPlane(initial_rows=16)
    cs = [rng.randrange(1, MODULUS) for _ in range(5)]
    weights = [[rng.randrange(0, 50) for _ in range(5)] for _ in range(3)]
    from dds_tpu.ops.montgomery import ModCtx

    rows = plane.rows_for("s0", MODULUS, cs)
    assert rows is not None and rows.shape == (5, ModCtx.make(MODULUS).L)
    assert fold_weighted(cs, weights, MODULUS, rows=rows) \
        == fold_weighted(cs, weights, MODULUS)


def test_sentry_resident_record_contract(tmp_path):
    from benchmarks.sentry import _check_resident_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "resident fold (S=4, K=64)", "value": 900.0,
        "unit": "folds/s", "vs_baseline": 2.4,
        "detail": {"shards": 4, "rows": 64, "cold_ms": 2.7, "warm_ms": 1.1},
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_resident_records(str(tmp_path)) == {"rows": 1}
    bad = dict(good, detail={"shards": 4, "rows": 64, "cold_ms": 2.7})
    (bench / "results.json").write_text(json.dumps([good, bad]))
    with pytest.raises(ValueError, match="malformed resident-fold record"):
        _check_resident_records(str(tmp_path))
