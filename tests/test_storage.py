"""Stratum tiered-storage tests (dds_tpu/storage).

Covers the ISSUE 20 acceptance surface: segment-store durability
(HMAC'd append-only log, fsync-before-rename, corrupt/truncated
quarantine to `*.corrupt`, crash-mid-demotion orphan adoption), the
keep-N manifest/compaction co-rotation invariant (pruning never strands
or deletes a segment the newest manifest names), eviction-to-warm with
a FROZEN reset counter (the silent fast-path-loss fix rides along as
the `resident_reset` incident + /health surface), the tier-planned fold
bit-for-bit vs an all-resident twin at 10x HBM capacity, Zipf-head
promotion back into the hot tier, restart reload of the cold tier, the
/metrics + /health "storage" surface, Helmsman's tier-pressure feed,
and the sentry `tiered fold` record contract.
"""

import asyncio
import json
import pathlib
import random

import pytest

from dds_tpu.core.snapshot import derive_secret, read_authenticated
from dds_tpu.obs.metrics import metrics
from dds_tpu.resident import ResidentPlane, ResidentPool
from dds_tpu.storage import (
    COLD,
    HOT,
    WARM,
    SegmentStore,
    Stratum,
    TierDirectory,
    WarmCache,
    derive_segment_secret,
)

pytestmark = pytest.mark.storage

rng = random.Random(0x57A7)
MODULUS = rng.getrandbits(256) | (1 << 255) | 1
L = 16  # 256-bit modulus at 16-bit limbs


def pyfold(cs, n=MODULUS):
    acc = 1
    for c in cs:
        acc = acc * c % n
    return acc


def _metric(name, **labels):
    return metrics.value(name, **labels) or 0


def _stripe(gid="g0", tenant="", modulus=MODULUS):
    return (gid, tenant, modulus)


def _population(k, seed=1):
    r = random.Random(seed)
    return [r.randrange(2, MODULUS) for _ in range(k)]


# -------------------------------------------------------- segment store


def test_segment_append_read_roundtrip(tmp_path):
    """A demotion wave persists durably and reads back as the exact limb
    rows of the appended ciphertexts (order + duplicates preserved)."""
    from dds_tpu.ops import bignum as bn

    store = SegmentStore(tmp_path, secret=b"seg-test")
    cs = _population(12)
    seq = store.append({_stripe(): cs})
    assert seq == 1
    assert all(store.contains(_stripe(), c) for c in cs)
    assert not store.contains(_stripe(), 999999999)
    want = [cs[3], cs[0], cs[3]]  # duplicates + order
    rows = store.read_rows(_stripe(), want, L)
    import numpy as np

    assert np.array_equal(
        rows, bn.ints_to_batch([c % MODULUS for c in want], L)
    )
    s = store.stats()
    assert s["rows"] == len(cs) and s["segments"] == 1
    assert s["generation"] == 1 and s["quarantined"] == 0
    with pytest.raises(KeyError):
        store.read_rows(_stripe(), [424242], L)


def test_segment_corrupt_and_truncated_quarantine_boot(tmp_path):
    """One flipped byte or a truncated tail quarantines that file to
    `*.corrupt` (mirroring snapshot v2) — boot indexes the survivors and
    never raises."""
    store = SegmentStore(tmp_path, secret=b"seg-test")
    a, b = _population(6), _population(6, seed=2)
    store.append({_stripe(): a})
    store.append({_stripe("g1"): b})
    segs = sorted(tmp_path.glob("stratum.segment.*.log"))
    assert len(segs) == 2
    # flip a byte mid-body in one, truncate the other
    raw = segs[0].read_bytes()
    segs[0].write_bytes(raw[:20] + b"X" + raw[21:])
    raw = segs[1].read_bytes()
    segs[1].write_bytes(raw[: len(raw) // 2])
    before = _metric("dds_segment_verify_failures_total")
    fresh = SegmentStore(tmp_path, secret=b"seg-test")
    assert fresh.load() == 0  # both waves lost, boot survives
    assert fresh.stats()["quarantined"] == 2
    assert _metric("dds_segment_verify_failures_total") == before + 2
    corrupts = sorted(p.name for p in tmp_path.glob("*.corrupt"))
    assert len(corrupts) == 2
    assert not list(tmp_path.glob("stratum.segment.*.log"))


def test_segment_wrong_secret_never_verifies(tmp_path):
    """Key/label separation: a store booted with a different secret
    quarantines every segment instead of trusting forged bytes, and the
    snapshot-label secret differs from the segment-label secret."""
    store = SegmentStore(tmp_path, secret=b"seg-A")
    store.append({_stripe(): _population(4)})
    other = SegmentStore(tmp_path, secret=b"seg-B")
    assert other.load() == 0
    assert other.stats()["quarantined"] >= 1
    assert derive_secret(b"base", None) != derive_segment_secret(b"base")


def test_manifest_keep_n_never_strands_live_segments(tmp_path):
    """The co-rotation invariant: manifests rotate keep-N, compaction
    prunes — but every file the NEWEST manifest names exists on disk,
    and a fresh load() indexes every live cipher."""
    store = SegmentStore(tmp_path, secret=b"seg-test", keep=2,
                         compact_segments=4)
    waves = [_population(5, seed=s) for s in range(10)]
    for i, wave in enumerate(waves):
        store.append({_stripe(f"g{i % 3}"): wave})
    manifests = sorted(tmp_path.glob("stratum.manifest.*.json"))
    assert 0 < len(manifests) <= 2  # keep-N rotated
    body = json.loads(
        read_authenticated(manifests[-1], store._secret).decode()
    )
    on_disk = {p.name for p in tmp_path.glob("stratum.segment.*.log")}
    for name in body["segments"]:
        assert name in on_disk, f"newest manifest names stranded {name}"
    # compaction ran and dropped dead files: disk holds exactly the live set
    assert store.stats()["compactions"] >= 1
    assert on_disk == set(body["segments"])
    fresh = SegmentStore(tmp_path, secret=b"seg-test")
    assert fresh.load() == sum(len(w) for w in waves)
    for i, wave in enumerate(waves):
        st = _stripe(f"g{i % 3}")
        assert all(fresh.contains(st, c) for c in wave)


def test_crash_mid_demotion_adopts_orphan_segments(tmp_path):
    """A crash between segment write and manifest write leaves an orphan
    file; the next boot verifies + ADOPTS it — no acked row lost — and
    re-manifests so compaction sees it live."""
    store = SegmentStore(tmp_path, secret=b"seg-test")
    store.append({_stripe(): _population(4)})
    orphan_cs = _population(5, seed=9)
    # simulate the crash: write the segment body directly, skip the manifest
    store._write_segment(2, {_stripe(): orphan_cs})
    fresh = SegmentStore(tmp_path, secret=b"seg-test")
    assert fresh.load() == 9
    assert all(fresh.contains(_stripe(), c) for c in orphan_cs)
    # the adopting boot wrote a new manifest generation naming the orphan
    newest = sorted(tmp_path.glob("stratum.manifest.*.json"))[-1]
    body = json.loads(
        read_authenticated(newest, store._secret).decode()
    )
    assert "stratum.segment.00000002.log" in body["segments"]


def test_discard_then_compact_reclaims_bytes(tmp_path):
    """Promotion is a logical delete; compaction rewrites the live set
    and the discarded ciphers are gone from the new segment."""
    store = SegmentStore(tmp_path, secret=b"seg-test")
    cs = _population(8)
    store.append({_stripe(): cs})
    assert store.discard(_stripe(), cs[:5]) == 5
    store.compact()
    assert store.stats()["rows"] == 3
    fresh = SegmentStore(tmp_path, secret=b"seg-test")
    fresh.load()
    assert sorted(fresh.entries()[_stripe()]) == sorted(cs[5:])
    assert not any(fresh.contains(_stripe(), c) for c in cs[:5])


def test_snapshot_and_segment_co_rotation_share_a_directory(tmp_path):
    """Satellite 3: snapshot v2 generations and segment manifests rotate
    keep-N side by side in one directory — neither family's pruning
    touches the other's files, and both reload cleanly after churn."""
    from dds_tpu.core import messages as M
    from dds_tpu.core import snapshot as snap
    from dds_tpu.core.replica import BFTABDNode, ReplicaConfig
    from dds_tpu.core.transport import InMemoryNet

    node = BFTABDNode("r0", ["r0", "r1"], "sup", InMemoryNet(),
                      ReplicaConfig(quorum_size=1))
    node._store("k", M.ABDTag(2, "r0"), [7, 9])
    store = SegmentStore(tmp_path, secret=b"seg-test", keep=2,
                         compact_segments=3)
    for s in range(6):
        snap.save_replica(node, tmp_path, secret=b"snap-test", keep=2)
        store.append({_stripe(): _population(3, seed=s)})
    # segment side intact after snapshot rotation (and vice versa)
    fresh = SegmentStore(tmp_path, secret=b"seg-test")
    assert fresh.load() == 18
    fresh_node = BFTABDNode("r0", ["r0", "r1"], "sup", InMemoryNet(),
                            ReplicaConfig(quorum_size=1))
    assert snap.load_replica(fresh_node, tmp_path, secret=b"snap-test")
    assert fresh_node.repository["k"] == (M.ABDTag(2, "r0"), [7, 9])
    assert len(list(tmp_path.glob("r0.snapshot.*.json"))) <= 2
    assert not list(tmp_path.glob("*.corrupt"))


# ------------------------------------------------- eviction-to-warm


def test_eviction_to_warm_freezes_reset_counter(tmp_path):
    """Tentpole invariant: with Stratum attached, driving 10x max_rows
    through a pool NEVER resets it — overflow demotes coldest-first into
    warm/cold and the resets counter stays 0."""
    plane = ResidentPlane(initial_rows=4, max_rows=16)
    stratum = Stratum(plane, tmp_path, warm_bytes=2048, chunk_rows=8)
    pop = _population(160)
    before_evict = _metric("dds_resident_evictions_total", shard="gE")
    for i in range(0, len(pop), 8):  # write-path style batched ingest
        plane.pool("gE", MODULUS).ingest(pop[i: i + 8])
    pool = plane.pool("gE", MODULUS)
    assert pool.resets == 0
    assert pool.resident <= 16
    assert _metric("dds_resident_evictions_total", shard="gE") \
        > before_evict
    tiers = stratum.stats()["tiers"]
    total = (pool.resident + tiers["warm"]["rows"] + tiers["cold"]["rows"])
    # warm rows of OTHER stripes may exist; count this stripe's entries
    st = ("gE", "", MODULUS)
    held = set(pool._index)
    held |= {c for s, c, _ in stratum.warm.items() if s == st}
    held |= set(stratum.cold.entries().get(st, ()))
    assert held == set(pop), "every ingested row is in exactly some tier"
    assert total >= len(pop)
    assert stratum.stats()["directory"]["hot"] >= 0


def test_eviction_protects_inflight_operands(tmp_path):
    """The eviction wave never evicts the operand set being ensured —
    otherwise ensure() would loop re-ingesting its own victims."""
    plane = ResidentPlane(initial_rows=4, max_rows=16)
    Stratum(plane, tmp_path, warm_bytes=4096)
    pool = plane.pool("gP", MODULUS)
    pool.ingest(_population(16, seed=3))  # fill to the brim
    cs = _population(12, seed=4)
    idx = pool.rows_for(cs)
    assert idx is not None
    assert all(c in pool._index for c in cs)
    assert pool.resets == 0


def test_reset_incident_filed_when_stratum_absent(tmp_path):
    """Satellite 1 regression: WITHOUT a tier sink the legacy capacity
    reset still happens — but now it files a `resident_reset` flight
    incident and stamps the pool for the /health age surface."""
    from dds_tpu.obs.flight import flight

    flight.configure(dir=str(tmp_path), min_interval=0.0)
    try:
        pool = ResidentPool(MODULUS, initial_rows=4, max_rows=8, gid="gR")
        pool.ingest(_population(8, seed=5))
        pool.ingest(_population(4, seed=6))  # 12 distinct > max_rows: reset
        assert pool.resets == 1
        assert pool.stats()["last_reset_age_s"] is not None
        incidents = list(tmp_path.glob("incident-*-resident_reset.jsonl"))
        assert len(incidents) == 1
        header = json.loads(incidents[0].read_text().splitlines()[0])
        assert header["incident"] == "resident_reset"
        assert header["info"]["shard"] == "gR"
        assert header["info"]["max_rows"] == 8
    finally:
        flight.configure(dir="")


def test_plane_stats_surface_resets_and_tiering(tmp_path):
    plane = ResidentPlane(initial_rows=4, max_rows=8)
    assert plane.stats()["tiered"] is False
    assert plane.stats()["resets"] == 0
    Stratum(plane, tmp_path)
    assert plane.stats()["tiered"] is True


# ------------------------------------------------- the tier planner


def test_tiered_fold_bit_for_bit_at_10x_capacity(tmp_path):
    """Acceptance flagship: one group holds 10x the pool's max_rows;
    SumAll-style folds (full population, hot subset, duplicates,
    cross-tier mixes) are bit-for-bit an all-resident twin's answers,
    with zero pool resets."""
    plane = ResidentPlane(initial_rows=4, max_rows=16)
    stratum = Stratum(plane, tmp_path, warm_bytes=1024, chunk_rows=8)
    twin = ResidentPlane(initial_rows=4, max_rows=1 << 14)
    pop = _population(160, seed=7)

    cases = [
        pop,                      # full population (10x capacity)
        pop[:10],                 # resident head
        pop[150:] * 3,            # cold tail with duplicates (MultAll)
        pop[::13] + pop[:3],      # cross-tier mix (SearchEq fold shape)
    ]
    for ops in cases:
        want = twin.fold_groups([("gF", ops)], MODULUS)
        assert want == pyfold(ops)
        assert stratum.fold_groups([("gF", ops)], MODULUS) == want
    assert plane.pool("gF", MODULUS).resets == 0
    s = stratum.stats()
    assert s["hits"]["warm"] + s["hits"]["cold"] > 0  # genuinely tiered
    assert s["tiers"]["cold"]["rows"] > 0


def test_tiered_fold_multi_group_and_empty(tmp_path):
    plane = ResidentPlane(initial_rows=4, max_rows=8)
    stratum = Stratum(plane, tmp_path, warm_bytes=512, chunk_rows=4)
    twin = ResidentPlane(initial_rows=4, max_rows=1 << 14)
    parts = [(f"s{i}", _population(40, seed=20 + i)) for i in range(3)]
    assert stratum.fold_groups(parts, MODULUS) \
        == twin.fold_groups(parts, MODULUS)
    assert stratum.fold_groups([], MODULUS) == 1 % MODULUS
    assert stratum.fold_groups([("s0", [])], MODULUS) == 1 % MODULUS


def test_zipf_head_promotes_back_to_hot(tmp_path):
    """Repeated folds over a tiered subset clear the promote-score bar
    and re-enter HBM: later folds serve them as hot hits."""
    plane = ResidentPlane(initial_rows=4, max_rows=16)
    stratum = Stratum(plane, tmp_path, warm_bytes=1024, chunk_rows=8,
                      promote_score=2.0)
    pop = _population(160, seed=8)
    stratum.fold_groups([("gZ", pop)], MODULUS)  # tier the population
    tail = pop[120:132]  # lives in warm/cold now
    want = pyfold(tail)
    stripe = ("gZ", "", MODULUS)
    for _ in range(3):
        assert stratum.fold_groups([("gZ", tail)], MODULUS) == want
    assert stratum.stats()["promotions"] >= len(tail)
    assert all(stratum.dir.tier_of(stripe, c) == HOT for c in tail)
    hot_before = stratum.stats()["hits"]["hot"]
    assert stratum.fold_groups([("gZ", tail)], MODULUS) == want
    assert stratum.stats()["hits"]["hot"] >= hot_before + len(tail)


def test_search_hits_feed_tier_promotion(tmp_path):
    """Spyglass selections speak keys; Stratum's write-time key->cipher
    map translates them into directory touches, and the warmed rows
    clear the promote bar at the next fold — searched-for rows re-enter
    HBM. Unmapped keys and a failing sink are both harmless."""
    from dds_tpu.search.plane import SearchPlane

    plane = ResidentPlane(initial_rows=4, max_rows=16)
    stratum = Stratum(plane, tmp_path, warm_bytes=4096, chunk_rows=8,
                      promote_score=2.0)
    search = SearchPlane()
    search.touch_sink = stratum.touch_keys
    pop = _population(160, seed=21)
    stratum.fold_groups([("gS", pop)], MODULUS)  # tier the population
    stripe = ("gS", "", MODULUS)
    tail = pop[150:156]  # demoted tail rows
    for i, c in enumerate(tail):
        stratum.note_write("gS", [c], key=f"k{i}")
    base = [stratum.dir.score(stripe, c) for c in tail]
    for _ in range(4):  # four queries keep finding the same keys
        search.note_selected([f"k{i}" for i in range(len(tail))])
    after = [stratum.dir.score(stripe, c) for c in tail]
    assert all(a > b for a, b in zip(after, base))
    search.note_selected(["never-written"])  # unmapped: no-op
    boom = stratum.touch_keys
    search.touch_sink = lambda keys, tenant: (_ for _ in ()).throw(
        RuntimeError("sink down"))
    search.note_selected(["k0"])  # advisory feed: swallowed, not raised
    search.touch_sink = boom
    want = pyfold(tail)
    assert stratum.fold_groups([("gS", tail)], MODULUS) == want
    assert all(stratum.dir.tier_of(stripe, c) == HOT for c in tail)


def test_restart_reloads_cold_tier_and_folds_exact(tmp_path):
    """Crash/restart: a fresh Stratum over the same directory reloads
    every HMAC-verified segment and the first fold is already exact."""
    plane = ResidentPlane(initial_rows=4, max_rows=16)
    stratum = Stratum(plane, tmp_path, warm_bytes=1024, chunk_rows=8)
    pop = _population(160, seed=11)
    want = pyfold(pop)
    assert stratum.fold_groups([("gB", pop)], MODULUS) == want
    cold_rows = stratum.cold.stats()["rows"]
    assert cold_rows > 0

    plane2 = ResidentPlane(initial_rows=4, max_rows=16)
    stratum2 = Stratum(plane2, tmp_path, warm_bytes=1024, chunk_rows=8)
    assert stratum2.cold.stats()["rows"] == cold_rows
    st = ("gB", "", MODULUS)
    assert all(stratum2.dir.tier_of(st, c) == COLD
               for c in stratum2.cold.entries()[st])
    assert stratum2.fold_groups([("gB", pop)], MODULUS) == want
    assert plane2.pool("gB", MODULUS).resets == 0


def test_tier_directory_decay_rank_orders_like_zipf():
    """The EWMA touch score rank-orders a Zipf access pattern: the head
    outscores the tail, and coldest() returns tail-first."""
    d = TierDirectory(half_life=60.0)
    st = _stripe()
    r = random.Random(5)
    items = list(range(40))
    weights = [1.0 / ((i + 1) ** 0.9) for i in items]
    total = sum(weights)
    for _ in range(2000):
        x = r.random() * total
        acc = 0.0
        for i, w in zip(items, weights):
            acc += w
            if acc >= x:
                d.touch(st, i)
                break
    order = [c for _, c in d.coldest([(st, i) for i in items])]
    head = set(items[:8])
    assert head & set(order[-12:]) == head, "Zipf head must rank hottest"
    assert d.score(st, items[0]) > d.score(st, items[-1])


def test_warm_cache_budget_and_pop():
    import numpy as np

    w = WarmCache(max_bytes=256)
    st = _stripe()
    row = np.ones(16, dtype=np.uint32)  # 64 bytes
    for c in range(5):
        w.put(st, c, row)
    assert w.bytes == 5 * 64
    assert w.over_budget() == 5 * 64 - 256
    assert w.contains(st, 3)
    got = w.pop(st, 3)
    assert got is not None and not w.contains(st, 3)
    assert w.pop(st, 3) is None
    assert len(w.items()) == 4


def test_stratum_pressure_feeds_helmsman(tmp_path):
    """pressure() rises toward 1.0 as the pool and warm budget fill —
    the Helmsman pool_pressure signal the run.py wiring reads."""
    plane = ResidentPlane(initial_rows=4, max_rows=16)
    stratum = Stratum(plane, tmp_path, warm_bytes=1 << 30)
    assert stratum.pressure() == 0.0
    plane.pool("gH", MODULUS).ingest(_population(16, seed=13))
    assert stratum.pressure() == 1.0  # pool at max_rows
    s = stratum.stats()
    assert s["pressure"] == 1.0


# ------------------------------------------------- server surface


def _rest_constellation(tmp_path, S=2, max_rows=8):
    from dds_tpu.core.transport import InMemoryNet
    from dds_tpu.http.server import DDSRestServer, ProxyConfig
    from dds_tpu.shard import build_constellation
    from dds_tpu.utils.config import ResidentConfig, StorageConfig

    net = InMemoryNet()
    const = build_constellation(net, shard_count=S, vnodes_per_group=8,
                                seed=3, n_active=4, n_sentinent=0, quorum=3)
    cfg = ProxyConfig(
        port=0, crypto_backend="cpu",
        resident=ResidentConfig(enabled=True, min_fold=1,
                                initial_rows=4, max_rows=max_rows),
        storage=StorageConfig(enabled=True, dir=str(tmp_path / "tiers"),
                              warm_bytes=2048, chunk_rows=8),
    )
    server = DDSRestServer(const.router, cfg)
    return server, const


def test_server_tier_surface_and_zero_resets(tmp_path):
    """End-to-end over HTTP: writes past the pool cap tier out instead
    of resetting; aggregates stay exact; /health grows a "storage"
    section and /metrics the dds_tier_* families; tier_pressure() serves
    the Helmsman signal."""
    from dds_tpu.http.miniserver import http_request
    from dds_tpu.models import HEKeys

    keys = HEKeys.generate(paillier_bits=512, rsa_bits=512)
    pk = keys.psse.public
    vals = list(range(1, 25))  # 24 rows through max_rows=8 pools

    async def go():
        server, const = _rest_constellation(tmp_path)
        await server.start()
        try:
            for v in vals:
                st, _ = await http_request(
                    "127.0.0.1", server.cfg.port, "POST", "/PutSet",
                    json.dumps(
                        {"contents": [str(pk.encrypt(v))]}
                    ).encode(),
                    timeout=10.0,
                )
                assert st == 200
            target = f"/SumAll?position=0&nsqr={pk.nsquare}"
            for _ in range(2):  # cold then tiered-warm pass
                st, body = await http_request(
                    "127.0.0.1", server.cfg.port, "GET", target,
                    timeout=30.0,
                )
                assert st == 200
                got = keys.psse.decrypt(int(json.loads(body)["result"]))
                assert got == sum(vals)
            assert server._stratum is not None
            assert server._resident.stats()["resets"] == 0
            assert 0.0 <= server.tier_pressure() <= 1.0
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/health",
                timeout=10.0)
            health = json.loads(body)
            assert "storage" in health
            for tier in ("hot", "warm", "cold"):
                assert tier in health["storage"]["tiers"]
            assert "resets" in health["resident"]
            assert "last_reset_age_s" in health["resident"]
            st, body = await http_request(
                "127.0.0.1", server.cfg.port, "GET", "/metrics",
                timeout=10.0)
            text = body.decode()
            assert "dds_tier_rows{" in text
            assert "dds_tier_hits_total{" in text
        finally:
            await server.stop()
            await const.stop()

    asyncio.run(go())


def test_chronoscope_classifies_tier_stages(tmp_path):
    """The tier movement spans land in Chronoscope's closed taxonomy."""
    from dds_tpu.obs.chronoscope import STAGES, classify

    for span, stage in (("tier.promote", "tier-promote"),
                        ("tier.demote", "tier-demote"),
                        ("tier.cold_read", "tier-cold-read")):
        assert classify(span) == stage
        assert stage in STAGES
    # the stages actually fire: demotion + cold read under real traffic
    from dds_tpu.utils.trace import tracer

    tracer.reset()
    plane = ResidentPlane(initial_rows=4, max_rows=8)
    stratum = Stratum(plane, tmp_path, warm_bytes=256, chunk_rows=4)
    pop = _population(64, seed=14)
    stratum.fold_groups([("gC", pop)], MODULUS)
    stratum.fold_groups([("gC", pop)], MODULUS)
    names = {r.name for r in tracer.events()}
    assert "tier.cold_read" in names


def test_sentry_tiered_record_contract(tmp_path):
    """Satellite 4: sentry --check validates `tiered fold` records —
    well-formed rows count, malformed rows (or a nonzero reset counter)
    exit-2 via ValueError, foreign rows are ignored."""
    from benchmarks.sentry import _check_tiered_records

    bench = tmp_path / "benchmarks"
    bench.mkdir()
    good = {
        "metric": "tiered fold (pop=640, hbm=64)", "value": 850.0,
        "unit": "folds/s", "vs_baseline": 0.97,
        "detail": {"max_rows": 64, "population": 640, "hot": 32,
                   "resets": 0, "cold_rows": 500, "warm_rows": 76,
                   "ceiling_ms": 1.1, "tiered_ms": 1.2},
    }
    (bench / "results.json").write_text(json.dumps([good]))
    assert _check_tiered_records(root=str(tmp_path)) == {"rows": 1}

    for breakage in (
        {"value": -1.0},
        {"detail": None},
        {"detail": {**good["detail"], "resets": 2}},
        {"detail": {**good["detail"], "population": 64}},  # not > max_rows
        {"detail": {**good["detail"], "tiered_ms": 0}},
    ):
        bad = {**good, **breakage}
        (bench / "results.json").write_text(json.dumps([good, bad]))
        with pytest.raises(ValueError, match="tiered-fold"):
            _check_tiered_records(root=str(tmp_path))

    foreign = {"metric": "resident fold (S=4, K=64)", "value": 1.0}
    (bench / "results.json").write_text(json.dumps([foreign]))
    assert _check_tiered_records(root=str(tmp_path)) == {"rows": 0}
