"""Known-answer tests for the Pallas Montgomery kernels (interpret mode).

Same vectors as the jnp-path tests: every kernel is validated against
python `pow` / `* %` big-int arithmetic. On CPU these run through the
Pallas interpreter (slow), so the modulus is kept small (256-bit); on a
real TPU the same code paths compile via Mosaic and are exercised at
Paillier-2048 scale by bench.py.
"""

import random

import numpy as np
import pytest

from dds_tpu.ops import bignum as bn
from dds_tpu.ops import pallas_mont as pm
from dds_tpu.ops.montgomery import ModCtx

INTERPRET = True  # compiled only on real TPU hardware


@pytest.fixture(scope="module")
def ctx():
    rng = random.Random(0xDD5)
    n = rng.getrandbits(256) | (1 << 255) | 1
    return ModCtx.make(n)


def test_mul_lm_matches_python(ctx):
    rng = random.Random(1)
    n = ctx.n
    K = 6
    a = [rng.randrange(n) for _ in range(K)]
    b = [rng.randrange(n) for _ in range(K)]
    R_inv = pow(1 << (16 * ctx.L), -1, n)
    alm = np.asarray(bn.ints_to_batch(a, ctx.L)).T
    blm = np.asarray(bn.ints_to_batch(b, ctx.L)).T
    out = pm.mul_lm(ctx, alm, blm, TB=128, interpret=INTERPRET)
    got = bn.batch_to_ints(np.asarray(out).T)
    assert got == [x * y * R_inv % n for x, y in zip(a, b)]


@pytest.mark.parametrize("K", [1, 2, 3, 5, 8])
def test_reduce_mul_matches_python(ctx, K):
    rng = random.Random(K)
    n = ctx.n
    cs = [rng.randrange(1, n) for _ in range(K)]
    out = pm.reduce_mul(ctx, bn.ints_to_batch(cs, ctx.L), interpret=INTERPRET)
    want = 1
    for c in cs:
        want = want * c % n
    assert bn.limbs_to_int(np.asarray(out)[0]) == want


@pytest.mark.parametrize("exp", [0, 1, 2, 65537, (1 << 64) + 12345])
def test_pow_mod_matches_python(ctx, exp):
    rng = random.Random(exp % 97)
    n = ctx.n
    bases = [rng.randrange(1, n) for _ in range(3)]
    out = pm.pow_mod(ctx, bn.ints_to_batch(bases, ctx.L), exp, interpret=INTERPRET)
    assert bn.batch_to_ints(np.asarray(out)) == [pow(b, exp, n) for b in bases]


def test_backend_pallas_fold_matches_cpu(ctx):
    from dds_tpu.models.backend import CpuBackend, TpuBackend

    rng = random.Random(7)
    n = ctx.n
    cs = [rng.randrange(1, n) for _ in range(9)]
    # min_device_batch=0: a 9-element fold must hit the Pallas kernel, not
    # the adaptive host fallback
    tpu = TpuBackend(pallas=True, min_device_batch=0)
    cpu = CpuBackend()
    assert tpu.modmul_fold(cs, n) == cpu.modmul_fold(cs, n)
    assert tpu.powmod_batch(cs[:2], 65537, n) == cpu.powmod_batch(cs[:2], 65537, n)
